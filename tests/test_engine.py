"""Unit tests for the plan -> execute chunk -> emit engine API.

The pure library split of run_consensus_dir (ROADMAP item 1): no
filesystem in planning or emission, cancellation only at chunk
boundaries, and output parity with the directory pipeline's writer.
"""

import os

import pytest

from repic_tpu.pipeline import engine
from repic_tpu.utils import box_io

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "mini10017"
)
BOX = 180


@pytest.fixture(scope="module")
def loaded():
    pickers = box_io.discover_picker_dirs(FIXTURE)
    names = box_io.micrograph_names(
        os.path.join(FIXTURE, pickers[0])
    )
    out = []
    for n in names:
        sets = box_io.load_micrograph_set(FIXTURE, pickers, n)
        assert sets is not None
        out.append((n, sets))
    return out


def test_options_validation():
    with pytest.raises(ValueError, match="exact"):
        engine.ConsensusOptions(solver="exact")
    with pytest.raises(ValueError, match="unknown option"):
        engine.ConsensusOptions.from_dict({"typo": 1})
    with pytest.raises(ValueError, match="JSON object"):
        engine.ConsensusOptions.from_dict([1])
    opts = engine.ConsensusOptions.from_dict(
        {"solver": "lp", "num_particles": 5, "use_mesh": False}
    )
    assert opts.solver == "lp" and opts.num_particles == 5


def test_plan_request_is_pure_and_bucketed(loaded):
    opts = engine.ConsensusOptions(use_mesh=False)
    plan = engine.plan_request(loaded, BOX, opts)
    # padded capacity lands on the {2^k, 1.5*2^k} bucket grid
    max_n = max(bs.n for _, sets in loaded for bs in sets)
    assert plan.capacity >= max_n
    assert plan.num_pickers == len(loaded[0][1])
    assert [n for c in plan.chunks for n in c.names] == [
        n for n, _ in loaded
    ]
    # same inputs -> same plan -> same bucket key (the warm handle)
    again = engine.plan_request(loaded, BOX, opts)
    assert again.bucket_key == plan.bucket_key
    with pytest.raises(ValueError):
        engine.plan_request([], BOX, opts)


def test_plan_request_chunks_under_forced_chunk(loaded, monkeypatch):
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "1")
    plan = engine.plan_request(
        loaded, BOX, engine.ConsensusOptions(use_mesh=False)
    )
    assert len(plan.chunks) == len(loaded)
    assert all(c.micrographs == 1 for c in plan.chunks)


def test_execute_emit_matches_directory_writer(loaded, tmp_path):
    """Engine emission == run_consensus_dir's BOX output, byte for
    byte (same renderer, same packed transfer)."""
    from repic_tpu.pipeline.consensus import run_consensus_dir

    out_dir = str(tmp_path / "ref")
    run_consensus_dir(FIXTURE, out_dir, BOX, use_mesh=False)
    emitted: dict[str, str] = {}
    for _part, batch, _res, packed, _s in engine.execute_request(
        loaded, BOX, engine.ConsensusOptions(use_mesh=False)
    ):
        engine.emit_box_chunk(
            batch, packed, BOX,
            sink=lambda f, c: emitted.__setitem__(f, c),
        )
    assert sorted(emitted) == sorted(
        f for f in os.listdir(out_dir) if f.endswith(".box")
    )
    for fname, content in emitted.items():
        with open(os.path.join(out_dir, fname)) as f:
            assert f.read() == content, fname


def test_cancel_only_at_chunk_boundaries(loaded, monkeypatch):
    """A cancel firing mid-run stops BETWEEN chunks: everything
    already yielded is complete, nothing half-done escapes."""
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "1")
    polls = []

    def cancel():
        # allow exactly one chunk, then report an expired deadline
        polls.append(1)
        return (
            "deadline exceeded (test)" if len(polls) > 1 else False
        )

    done = []
    with pytest.raises(engine.ConsensusCancelled, match="deadline"):
        for part, batch, _res, packed, _s in engine.execute_request(
            loaded, BOX,
            engine.ConsensusOptions(use_mesh=False),
            cancel=cancel,
        ):
            counts = engine.emit_box_chunk(
                batch, packed, BOX, sink=lambda f, c: None
            )
            done.append((part[0][0], counts))
    assert len(done) == 1  # one complete chunk, then the boundary
    assert done[0][1][loaded[0][0]] > 0


def test_warmup_compiles_smallest_bucket():
    info = engine.warmup()
    assert info["num_pickers"] == 2
    assert info["capacity"] == 64
    assert info["compile_s"] >= 0


def test_chunk_program_contract_registered():
    from repic_tpu.analysis.contracts import registry

    assert (
        "repic_tpu.pipeline.engine.consensus_chunk_program"
        in registry()
    )
