"""End-to-end consensus on the committed fixture set.

Unlike tests/test_golden_10017.py (which needs the reference mount),
this runs against ``tests/fixtures/mini10017/`` — a committed,
deterministically synthesized 3-picker x 3-micrograph dataset — so
golden-style coverage survives without any external data.  The
expected snapshot was produced by tests/fixtures/make_fixture.py.
"""

import json
import os

import numpy as np

from repic_tpu.ops.solver import solve_exact_py
from repic_tpu.pipeline.consensus import run_consensus_dir
from repic_tpu.utils import box_io

HERE = os.path.join(os.path.dirname(__file__), "fixtures")
FIXTURE = os.path.join(HERE, "mini10017")
EXPECTED = os.path.join(HERE, "mini10017_expected.json")


def test_fixture_consensus_matches_snapshot(tmp_path):
    with open(EXPECTED) as f:
        expected = json.load(f)
    out = str(tmp_path / "out")
    stats = run_consensus_dir(
        FIXTURE, out, expected["box_size"], use_mesh=False
    )
    assert sorted(stats["pickers"]) == expected["pickers"]
    assert stats["num_cliques"] == expected["num_cliques"]
    assert stats["particle_counts"] == expected["particle_counts"]
    for name, count in expected["particle_counts"].items():
        rows = open(os.path.join(out, name + ".box")).read().splitlines()
        assert len(rows) == count
        weights = [float(r.split("\t")[4]) for r in rows]
        assert weights == sorted(weights, reverse=True)


def test_fixture_solver_within_gate_of_exact(tmp_path):
    """The committed fixture also gates the solver against the exact
    oracle, mirroring the reference-mount golden test."""
    from repic_tpu.parallel.batching import pad_batch
    from repic_tpu.pipeline.consensus import run_consensus_batch

    with open(EXPECTED) as f:
        expected = json.load(f)
    pickers = box_io.discover_picker_dirs(FIXTURE)
    names = box_io.micrograph_names(os.path.join(FIXTURE, pickers[0]))
    loaded = [
        (n, box_io.load_micrograph_set(FIXTURE, pickers, n))
        for n in names
    ]
    batch = pad_batch(loaded)
    res = run_consensus_batch(
        batch, float(expected["box_size"]), use_mesh=False
    )
    k = len(pickers)
    for i in range(len(names)):
        valid = np.asarray(res.valid[i])
        mem = np.asarray(res.member_idx[i])[valid]
        w = np.asarray(res.w[i])[valid]
        picked = np.asarray(res.picked[i])[valid]
        vid = mem + np.arange(k)[None, :] * batch.capacity
        exact = solve_exact_py(vid, w.astype(np.float64))
        assert w[picked].sum() >= 0.98 * w[exact].sum()


def test_fixture_sigmoid_path_exercised():
    """The gamma picker stores log-likelihood confidences; loading
    must sigmoid them into (0, 1) (reference common.py:92-94)."""
    bs = box_io.read_box(
        os.path.join(FIXTURE, "gamma", "mic_000.box")
    )
    raw = np.loadtxt(
        os.path.join(FIXTURE, "gamma", "mic_000.box"), usecols=4
    )
    assert (raw < 0).any()  # file really holds log-likelihoods
    assert (np.asarray(bs.conf) > 0).all()
    assert (np.asarray(bs.conf) < 1).all()
