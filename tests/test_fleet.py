"""Serving-fleet tests: leases, fencing, steal, exactly-once commit.

The ISSUE 11 surface at the unit/in-process level (the subprocess
SIGKILL chaos gate is tests/test_fleet_chaos.py): per-job ``O_EXCL``
leases are exclusive across replicas; a dead replica is fenced and
its leases stolen (with the ``lease_steal`` fault exercising the
lost-race branch); terminal states commit exactly once through the
completion token and a fenced replica cannot commit at all;
idempotency keys dedupe retries fleet-wide; any replica answers
GET/DELETE for any job; the 429 backoff is fleet-aware.
"""

import json
import os

import pytest

from repic_tpu.runtime import faults
from repic_tpu.runtime.atomic import commit_once
from repic_tpu.runtime.cluster import fence_path
from repic_tpu.runtime.journal import _read_entries
from repic_tpu.serve.fleet import (
    FleetMember,
    FleetQueue,
    done_path,
    job_lease_path,
    resolve_replica_id,
)
from repic_tpu.serve.jobs import (
    JOB_FINISHED,
    JOB_QUEUED,
    JOB_RUNNING,
    TERMINAL_STATES,
    AdmissionError,
    ServeJournal,
)

REQ = {"in_dir": "/tmp", "box_size": 180, "options": {}}


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _member(fleet, rid, clk, timeout=1.0):
    m = FleetMember(
        str(fleet),
        rid,
        heartbeat_interval_s=0.2,
        replica_timeout_s=timeout,
        clock=clk,
    )
    # no renewal thread in unit tests: heartbeats are explicit beats
    # against the injectable clock, so liveness is deterministic
    m.ctx.beat()
    return m


def _queue(fleet, member, limit=8, clk=None):
    return FleetQueue(
        limit,
        ServeJournal(str(fleet), replica=member.replica),
        member,
        clock=clk or member._clock,
    )


def _all_state_records(fleet, job_id):
    import glob

    out = []
    for path in sorted(
        glob.glob(os.path.join(str(fleet), "_serve_journal*.jsonl"))
    ):
        out.extend(
            e
            for e in _read_entries(path)
            if e.get("job") == job_id
            and "state" in e
            and "event" not in e
        )
    return out


# -- primitives -------------------------------------------------------


def test_commit_once_is_exclusive_and_complete(tmp_path):
    path = str(tmp_path / "token.json")
    assert commit_once(path, '{"winner": 1}') is True
    assert commit_once(path, '{"winner": 2}') is False
    with open(path) as f:
        assert json.load(f) == {"winner": 1}
    # no temp litter
    assert os.listdir(tmp_path) == ["token.json"]


def test_resolve_replica_id_env_and_sanitize(monkeypatch):
    monkeypatch.setenv("REPIC_TPU_REPLICA_ID", "rack1/node 2")
    assert resolve_replica_id() == "rack1_node_2"
    monkeypatch.delenv("REPIC_TPU_REPLICA_ID")
    # default is hostname+pid: pid alone collides across machines
    # sharing one fleet dir
    rid = resolve_replica_id()
    assert rid.endswith(f"-{os.getpid()}")
    from repic_tpu.runtime.journal import sanitize_host_id

    assert rid == sanitize_host_id(rid)  # filename-safe as-is


def test_job_lease_is_exclusive(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    b = _member(tmp_path, "b", clk)
    assert a.lease_job("job-x") is True
    assert b.lease_job("job-x") is False
    assert a.lease_info("job-x")["replica"] == "a"
    # only the owner can release
    b.release_lease("job-x")
    assert a.lease_info("job-x") is not None
    a.release_lease("job-x")
    assert a.lease_info("job-x") is None


def test_commit_terminal_exactly_once(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    b = _member(tmp_path, "b", clk)
    assert a.commit_terminal("job-x", JOB_FINISHED) is None
    lost = b.commit_terminal("job-x", "failed")
    assert lost is not None
    assert lost["state"] == JOB_FINISHED
    assert lost["replica"] == "a"


def test_fenced_replica_cannot_commit(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    b = _member(tmp_path, "b", clk)
    # b fences a (the survivor path); a then wakes and tries to emit
    clk.advance(5.0)
    b.ctx.beat()
    st = b.liveness()["a"]
    assert st.rung == "suspect"
    assert b._fence_replica("a", st) is True
    res = a.commit_terminal("job-y", JOB_FINISHED)
    assert res is not None  # commit refused
    assert not os.path.exists(done_path(str(tmp_path), "job-y"))


# -- harvest: fence + steal -------------------------------------------


def _orphan_setup(tmp_path, clk):
    """Replica a accepts+leases a job, then dies (heartbeat ages
    out); returns (b, qb, job_id)."""
    a = _member(tmp_path, "a", clk)
    ja = ServeJournal(str(tmp_path), replica="a")
    ja.record("job-orph", JOB_QUEUED, request=REQ, trace="t1")
    ja.record("job-orph", JOB_RUNNING, trace="t1")
    ja.close()
    assert a.lease_job("job-orph")
    clk.advance(5.0)  # a's heartbeat is now ancient
    b = _member(tmp_path, "b", clk)
    return b, _queue(tmp_path, b), "job-orph"


def test_harvest_fences_dead_replica_and_steals_lease(tmp_path):
    clk = Clock()
    b, qb, jid = _orphan_setup(tmp_path, clk)
    stolen = b.harvest(qb.fleet_view(), qb.journal)
    assert stolen == [jid]
    lease = b.lease_info(jid)
    assert lease["replica"] == "b"
    assert lease["epoch"] == 2
    assert lease["stolen_from"] == "a"
    assert os.path.exists(fence_path(str(tmp_path), "a"))
    events = [
        e.get("event")
        for e in _read_entries(qb.journal.path)
    ]
    assert "replica_fenced" in events
    assert "job_reassigned" in events
    # the stolen job surfaces through the scheduler as a resumed run
    job = qb.next_job(0.1)
    assert job is not None and job.id == jid
    assert job.resumed is True
    assert job.trace_id == "t1"  # the accept's trace id survives


@pytest.mark.faults
def test_lease_steal_fault_loses_the_race(tmp_path):
    clk = Clock()
    b, qb, jid = _orphan_setup(tmp_path, clk)
    with faults.fault_plan("lease_steal::1"):
        assert b.harvest(qb.fleet_view(), qb.journal) == []
        assert b.lease_info(jid)["replica"] == "a"
        # plan spent: the next harvest round wins the takeover
        assert b.harvest(qb.fleet_view(), qb.journal) == [jid]
    assert b.lease_info(jid)["replica"] == "b"


def test_steal_budget_quarantines_poison_job(tmp_path):
    """ISSUE 14: the lease-steal is where a poison pill would
    propagate, so the retry budget is checked there.  A job whose
    journaled run attempts already exceed the budget is NOT stolen
    — the fence winner commits it terminal ``quarantined`` through
    the exactly-once token, with one terminal record, and the job
    can never be claimed again."""
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    ja = ServeJournal(str(tmp_path), replica="a")
    ja.record("job-poison", JOB_QUEUED, request=REQ, trace="tp",
              tenant="teamA")
    # two journaled run attempts (original + one failover re-run):
    # over a budget of 1
    ja.record("job-poison", JOB_RUNNING, trace="tp")
    ja.record("job-poison", JOB_RUNNING, resumed=True, trace="tp")
    ja.close()
    assert a.lease_job("job-poison")
    clk.advance(5.0)  # a dies
    b = _member(tmp_path, "b", clk)
    b.reassign_budget = 1
    qb = _queue(tmp_path, b)
    stolen = b.harvest(qb.fleet_view(), qb.journal)
    assert stolen == []  # quarantined, not stolen
    # exactly-once: the completion token carries the state
    done = b.read_done("job-poison")
    assert done["state"] == "quarantined"
    assert done["attempts"] == 2
    # the lease still names the dead replica (never rewritten), but
    # the token forecloses scheduling it anywhere
    assert b.lease_info("job-poison")["replica"] == "a"
    assert qb.next_job(0.05) is None
    records = _all_state_records(tmp_path, "job-poison")
    terminal = [
        r for r in records if r["state"] in TERMINAL_STATES
    ]
    assert len(terminal) == 1
    assert terminal[0]["state"] == "quarantined"
    assert terminal[0]["trace"] == "tp"
    assert "retry budget" in terminal[0]["reason"]
    # any replica answers GET with the quarantined materialization
    job = qb.get("job-poison")
    assert job.state == "quarantined"
    assert job.tenant == "teamA"
    # a second harvest round has nothing left to do
    assert b.harvest(qb.fleet_view(), qb.journal) == []


def test_steal_within_budget_still_steals(tmp_path):
    """One prior run attempt is within the default budget (2): the
    steal proceeds exactly as before ISSUE 14."""
    clk = Clock()
    b, qb, jid = _orphan_setup(tmp_path, clk)
    assert b.reassign_budget == 2
    assert b.harvest(qb.fleet_view(), qb.journal) == [jid]
    assert b.read_done(jid) is None


def test_recover_own_quarantines_over_budget(tmp_path):
    """The restart-recovery half: a replica restarting under the
    same id, still holding the lease of a job that crashed it
    repeatedly, quarantines it instead of re-running into the same
    crash — and releases its lease."""
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    ja = ServeJournal(str(tmp_path), replica="a")
    ja.record("job-own", JOB_QUEUED, request=REQ, trace="to")
    for _ in range(2):
        ja.record("job-own", JOB_RUNNING, trace="to")
    ja.close()
    assert a.lease_job("job-own")
    # "restart": a fresh member under the same id
    a2 = _member(tmp_path, "a", clk)
    a2.reassign_budget = 1
    qa2 = _queue(tmp_path, a2)
    assert qa2.recover_own() == []
    done = a2.read_done("job-own")
    assert done["state"] == "quarantined"
    assert a2.lease_info("job-own") is None  # lease released
    assert qa2.get("job-own").state == "quarantined"
    terminal = [
        r
        for r in _all_state_records(tmp_path, "job-own")
        if r["state"] in TERMINAL_STATES
    ]
    assert len(terminal) == 1


def test_harvest_leaves_live_replicas_alone(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    ja = ServeJournal(str(tmp_path), replica="a")
    ja.record("job-live", JOB_QUEUED, request=REQ)
    ja.close()
    assert a.lease_job("job-live")
    b = _member(tmp_path, "b", clk)
    qb = _queue(tmp_path, b)
    a.ctx.beat()  # a is demonstrably alive
    assert b.harvest(qb.fleet_view(), qb.journal) == []
    assert b.lease_info("job-live")["replica"] == "a"


# -- the fleet queue --------------------------------------------------


def test_submit_claim_run_finish_exactly_once(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    job = qa.submit(dict(REQ))
    assert job.state == JOB_QUEUED
    got = qa.next_job(0.1)
    assert got is job
    assert a.lease_info(job.id)["replica"] == "a"
    qa.mark_running(job)
    qa.finish(job, JOB_FINISHED, particles=7)
    done = a.read_done(job.id)
    assert done["state"] == JOB_FINISHED
    assert a.lease_info(job.id) is None  # released after commit
    records = _all_state_records(tmp_path, job.id)
    terminal = [
        r for r in records if r["state"] in TERMINAL_STATES
    ]
    assert len(terminal) == 1
    assert terminal[0]["particles"] == 7


def test_commit_lost_adopts_winner_state(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    b = _member(tmp_path, "b", clk)
    qa = _queue(tmp_path, a)
    job = qa.submit(dict(REQ))
    assert qa.next_job(0.1) is job
    qa.mark_running(job)
    # a survivor (b) already committed this job
    assert b.commit_terminal(job.id, JOB_FINISHED) is None
    qa.finish(job, "failed", error={"type": "X"})
    assert job.state == JOB_FINISHED  # adopted, not overwritten
    # the loser journaled NO terminal state record (only the
    # commit_lost event) — the completion token is the authority,
    # and every replica's view folds it in
    terminal = [
        r
        for r in _all_state_records(tmp_path, job.id)
        if r["state"] in TERMINAL_STATES
    ]
    assert terminal == []
    events = [
        e.get("event") for e in _read_entries(qa.journal.path)
    ]
    assert "commit_lost" in events
    assert qa.get(job.id).state == JOB_FINISHED
    b_view = _queue(tmp_path, b)
    assert b_view.get(job.id).state == JOB_FINISHED


def test_any_replica_answers_get(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    job = qa.submit(dict(REQ), deadline_s=60.0)
    b = _member(tmp_path, "b", clk)
    qb = _queue(tmp_path, b)
    doc = qb.get(job.id)
    assert doc is not None
    assert doc.state == JOB_QUEUED
    assert doc.request == REQ
    assert doc.trace_id == job.trace_id
    assert {j.id for j in qb.jobs()} >= {job.id}
    # terminal outcome propagates too
    assert qa.next_job(0.1) is job
    qa.mark_running(job)
    qa.finish(job, JOB_FINISHED, particles=3)
    doc2 = qb.get(job.id)
    assert doc2.state == JOB_FINISHED
    assert doc2.result.get("particles") == 3


def test_cancel_queued_job_from_another_replica(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    job = qa.submit(dict(REQ))
    b = _member(tmp_path, "b", clk)
    qb = _queue(tmp_path, b)
    got = qb.cancel(job.id)
    assert got.state == "cancelled"
    assert b.read_done(job.id)["state"] == "cancelled"
    # the original replica sees the cancellation and never runs it
    assert qa.next_job(0.05) is None
    assert qa.get(job.id).state == "cancelled"
    terminal = [
        r
        for r in _all_state_records(tmp_path, job.id)
        if r["state"] in TERMINAL_STATES
    ]
    assert len(terminal) == 1


def test_cancel_running_job_rides_the_journal(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    job = qa.submit(dict(REQ))
    assert qa.next_job(0.1) is job
    qa.mark_running(job)
    b = _member(tmp_path, "b", clk)
    qb = _queue(tmp_path, b)
    got = qb.cancel(job.id)
    assert got.cancel_requested is True
    # the runner's chunk-boundary poll sees the request
    assert qa.cancel_requested_remote(job.id) is True


def test_idempotent_submit_dedupes_across_replicas(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    job, deduped = qa.submit_idempotent(
        dict(REQ), idempotency_key="k-1"
    )
    assert deduped is False
    again, deduped2 = qa.submit_idempotent(
        dict(REQ), idempotency_key="k-1"
    )
    assert deduped2 is True and again.id == job.id
    b = _member(tmp_path, "b", clk)
    qb = _queue(tmp_path, b)
    other, deduped3 = qb.submit_idempotent(
        dict(REQ), idempotency_key="k-1"
    )
    assert deduped3 is True and other.id == job.id
    fresh, deduped4 = qb.submit_idempotent(
        dict(REQ), idempotency_key="k-2"
    )
    assert deduped4 is False and fresh.id != job.id


@pytest.mark.faults
def test_fleet_retry_after_spreads_over_live_replicas(tmp_path):
    """Per-micrograph Retry-After: the 429 estimate is per-micrograph
    service time x fleet-wide QUEUED MICROGRAPHS / live replicas —
    the whole-job average over-estimated under continuous batching
    (a queued job's micrographs, not the job, are the service unit)."""
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    b = _member(tmp_path, "b", clk)
    qa = _queue(tmp_path, a, limit=1)
    qa._avg_mic_s = 10.0
    qa.submit(dict(REQ), micrographs=4)
    with pytest.raises(AdmissionError) as exc:
        qa.submit(dict(REQ))
    assert exc.value.http_status == 429
    # 4 queued micrographs x 10 s/mic over 2 live replicas -> ~20 s,
    # not the ~40 s a whole-job estimate would claim
    assert exc.value.retry_after_s == 20
    del b  # (b's heartbeat is on disk either way)


def test_concurrent_same_key_submits_yield_one_job(tmp_path):
    """Review regression: N threads retrying ONE idempotency key
    against one replica must produce exactly one journaled job —
    the creation-lock re-check, not just the pre-scan."""
    import threading

    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    results = []
    go = threading.Barrier(6)

    def hammer():
        go.wait(5)
        results.append(
            qa.submit_idempotent(dict(REQ), idempotency_key="k")
        )

    threads = [threading.Thread(target=hammer) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    ids = {job.id for job, _ in results}
    assert len(ids) == 1, ids
    assert sum(1 for _, deduped in results if not deduped) == 1
    queued = [
        r
        for r in _read_entries(qa.journal.path)
        if r.get("state") == JOB_QUEUED and "event" not in r
    ]
    assert len(queued) == 1


def test_skewed_running_record_keeps_the_accept_payload(tmp_path):
    """Review regression: a peer's `running` record whose clock
    sorts BEFORE the accept record must not become the fold's
    `first` — request/trace/idempotency_key live on the accept."""
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    ja = ServeJournal(str(tmp_path), replica="a")
    ja.record(
        "job-skew", JOB_QUEUED, request=REQ, trace="t9",
        idempotency_key="kx",
    )
    ja.close()
    # replica b's clock runs 5 s behind: its running record's ts
    # sorts before the accept
    jb = ServeJournal(str(tmp_path), replica="b")
    entry = jb.record("job-skew", JOB_RUNNING, trace="t9")
    jb.close()
    import json as _json

    lines = open(jb.path).read().splitlines()
    entry["ts"] -= 5.0
    with open(jb.path, "w") as f:
        for line in lines[:-1]:
            f.write(line + "\n")
        f.write(_json.dumps(entry) + "\n")
    qa = _queue(tmp_path, a)
    info = qa.fleet_view()["job-skew"]
    assert info["first"].get("request") == REQ
    job = qa.get("job-skew")
    assert job.request == REQ
    assert job.trace_id == "t9"
    assert job.idempotency_key == "kx"


def test_recover_own_after_restart(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    job = qa.submit(dict(REQ))
    assert qa.next_job(0.1) is job
    qa.mark_running(job)
    qa.journal.close()
    # same replica id restarts: it still holds the lease
    a2 = _member(tmp_path, "a", clk)
    qa2 = _queue(tmp_path, a2)
    recovered = qa2.recover_own()
    assert [j.id for j in recovered] == [job.id]
    assert recovered[0].resumed is True
    assert recovered[0].trace_id == job.trace_id


def test_orphaned_leases_listing_and_drain_release(tmp_path):
    clk = Clock()
    a = _member(tmp_path, "a", clk)
    qa = _queue(tmp_path, a)
    job = qa.submit(dict(REQ))
    assert qa.next_job(0.1) is job
    qa.mark_running(job)
    # a live replica's in-flight lease is healthy, not orphaned
    assert a.orphaned_leases() == []
    clk.advance(5.0)  # the holder's heartbeat ages out
    assert a.orphaned_leases() == [job.id]
    a.ctx.beat()
    # drain hand-back: queued again, lease released
    qa.finish(job, JOB_QUEUED, reason="draining past grace")
    assert a.orphaned_leases() == []
    assert not os.path.exists(
        job_lease_path(str(tmp_path), job.id)
    )
    view = qa.fleet_view()
    assert view[job.id]["state"] == JOB_QUEUED


@pytest.mark.faults
def test_replica_crash_site_is_known():
    assert "replica_crash" in faults.KNOWN_SITES
    assert "lease_steal" in faults.KNOWN_SITES


# -- daemon integration (in-process, real engine over the fixture) ----

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "mini10017"
)
SUBMIT = {
    "in_dir": FIXTURE,
    "box_size": 180,
    "options": {"use_mesh": False},
}
TERMINAL_DOC = (
    "finished", "failed", "cancelled", "deadline_exceeded"
)


def _req(port, method, path, body=None, timeout=30):
    import urllib.error
    import urllib.request

    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=(
            json.dumps(body).encode() if body is not None else None
        ),
    )
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _wait_terminal(port, job_id, timeout=120):
    import time as _time

    deadline = _time.time() + timeout
    while _time.time() < deadline:
        code, body = _req(port, "GET", f"/v1/jobs/{job_id}")
        assert code == 200, body
        doc = json.loads(body)
        if doc["state"] in TERMINAL_DOC:
            return doc
        _time.sleep(0.05)
    raise AssertionError(f"job {job_id} never became terminal")


def test_fleet_daemon_end_to_end(tmp_path):
    """One-replica fleet over HTTP: submit -> finished with the
    shared-queue machinery (lease, commit token, per-replica trace),
    the /status fleet + breaker sections, and an idempotent retry
    answered 200 with the original id."""
    from repic_tpu.serve.daemon import ConsensusDaemon

    fleet = str(tmp_path / "fleet")
    d = ConsensusDaemon(
        str(tmp_path / "wd"),
        port=0,
        warmup=False,
        fleet_dir=fleet,
        replica_id="r1",
        heartbeat_interval_s=0.2,
        replica_timeout_s=1.0,
    )
    d.start()
    try:
        port = d.server.port
        code, body = _req(
            port, "POST", "/v1/jobs",
            dict(SUBMIT, idempotency_key="key-a"),
        )
        assert code == 202, body
        doc0 = json.loads(body)
        jid = doc0["id"]
        doc = _wait_terminal(port, jid)
        assert doc["state"] == "finished", doc
        assert doc["replica"] == "r1"
        # exactly-once machinery left its artifacts
        done = json.load(
            open(os.path.join(fleet, f"_done.{jid}.json"))
        )
        assert done["state"] == "finished"
        assert not os.path.exists(
            os.path.join(fleet, f"_joblease.{jid}.json")
        )
        # job output lives in the SHARED fleet tree
        assert os.path.isdir(os.path.join(fleet, "jobs", jid))
        code, body = _req(port, "GET", f"/v1/jobs/{jid}/artifacts")
        assert code == 200
        assert len(json.loads(body)["artifacts"]) == 3
        # per-replica trace artifact under the accept-time trace id
        from repic_tpu.telemetry.trace import read_trace

        tr_path = os.path.join(
            fleet, "jobs", jid, "_trace.r1.jsonl"
        )
        assert os.path.exists(tr_path)
        assert any(
            r.get("trace") == doc["trace_id"]
            for r in read_trace(os.path.join(fleet, "jobs", jid))
        )
        # /status: fleet section with live replica + breaker detail
        status = json.loads(_req(port, "GET", "/status")[1])
        assert status["fleet"]["replica"] == "r1"
        assert (
            status["fleet"]["replicas"]["r1"]["rung"] == "live"
        )
        assert status["fleet"]["orphaned_leases"] == 0
        assert status["breaker"]["state"] == "closed"
        assert status["breaker"]["consecutive_failures"] == 0
        metrics = _req(port, "GET", "/metrics")[1]
        assert "repic_serve_breaker_state 0" in metrics
        assert "repic_serve_breaker_failures 0" in metrics
        assert "repic_fleet_replicas_live" in metrics
        # idempotent retry: 200, same job, deduped flag
        code, body = _req(
            port, "POST", "/v1/jobs",
            dict(SUBMIT, idempotency_key="key-a"),
        )
        assert code == 200, body
        retry = json.loads(body)
        assert retry["id"] == jid
        assert retry["deduped"] is True
    finally:
        d.drain()
    # a clean drain leaves zero orphaned leases behind
    probe = FleetMember(fleet, "probe")
    assert probe.orphaned_leases() == []


def test_fleet_two_daemons_share_one_queue(tmp_path):
    """Two live replicas, one fleet dir: submissions to one replica
    are visible (doc + artifacts) from the other, and every job
    finishes exactly once somewhere in the fleet."""
    from repic_tpu.serve.daemon import ConsensusDaemon

    fleet = str(tmp_path / "fleet")
    ds = [
        ConsensusDaemon(
            str(tmp_path / f"wd{i}"),
            port=0,
            warmup=False,
            fleet_dir=fleet,
            replica_id=f"r{i}",
            heartbeat_interval_s=0.2,
            replica_timeout_s=1.0,
        ).start()
        for i in (1, 2)
    ]
    try:
        p1, p2 = (d.server.port for d in ds)
        ids = []
        # submitted sequentially (next only after the previous is
        # terminal): two jobs running at once in ONE process would
        # interleave the run-scoped global event log — a test-only
        # hazard (real replicas are separate processes)
        for _ in range(2):
            code, body = _req(p1, "POST", "/v1/jobs", SUBMIT)
            assert code == 202, body
            jid = json.loads(body)["id"]
            ids.append(jid)
            _wait_terminal(p2, jid)
        for jid in ids:
            # poll the OTHER replica: any replica answers any job
            doc = _wait_terminal(p2, jid)
            assert doc["state"] == "finished", doc
            assert doc["replica"] in ("r1", "r2")
            code, body = _req(
                p2, "GET", f"/v1/jobs/{jid}/artifacts"
            )
            assert code == 200
            assert len(json.loads(body)["artifacts"]) == 3
            terminal = [
                r
                for r in _all_state_records(fleet, jid)
                if r["state"] in TERMINAL_STATES
            ]
            assert len(terminal) == 1, terminal
        # the job list on either replica covers the whole fleet
        listing = json.loads(_req(p2, "GET", "/v1/jobs")[1])
        assert {j["id"] for j in listing["jobs"]} >= set(ids)
    finally:
        for d in ds:
            d.drain()
