"""Fleet chaos: SIGKILL a replica mid-job; drain the fleet clean.

The ISSUE 11 acceptance gate, end to end over real processes:

* three `repic-tpu serve --fleet-dir` replicas on ephemeral ports;
  a job is submitted to one of them, and whichever replica is
  RUNNING it is SIGKILLed after its first chunk lands (the 12-
  micrograph examples/10017 set at chunk=1 guarantees plenty of
  mid-job window).  The job must finish on a survivor under the
  client's ORIGINAL job id, with byte-identical artifacts to an
  undisturbed control run, exactly one terminal journal record,
  exactly one completion token, a journaled reassignment, and a
  trace whose records span both replicas under one trace id.
* a fleet drain (SIGTERM everything) exits rc 0 everywhere and
  leaves zero orphaned leases.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from repic_tpu.runtime.journal import _read_entries
from repic_tpu.serve.jobs import TERMINAL_STATES

EXAMPLES = os.path.join(
    os.path.dirname(__file__), "..", "examples", "10017"
)
FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "mini10017"
)


def _spawn_replica(
    fleet, wd, rid, hb="0.2", timeout="1.0", extra_env=None,
    warmup=False, extra_args=(),
):
    os.makedirs(wd, exist_ok=True)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        REPIC_TPU_NO_CONFIG_CACHE="1",
        REPIC_CONSENSUS_CHUNK="1",
        REPIC_TPU_REPLICA_ID=rid,
    )
    env.pop("REPIC_TPU_FAULTS", None)
    env.update(extra_env or {})
    return subprocess.Popen(
        [
            sys.executable, "-m", "repic_tpu.main", "serve", wd,
            "--port", "0",
            *(() if warmup else ("--no-warmup",)),
            "--fleet-dir", fleet,
            "--heartbeat-interval", hb,
            "--replica-timeout", timeout,
            *extra_args,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )


def _wait_port(wd, proc, deadline_s=90):
    info_path = os.path.join(wd, "_serve.json")
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                "replica died at startup:\n" + proc.communicate()[0]
            )
        try:
            with open(info_path) as f:
                info = json.load(f)
            if info.get("pid") == proc.pid:
                return info["port"]
        except (OSError, ValueError):
            pass
        time.sleep(0.1)
    proc.kill()
    raise AssertionError("replica never wrote _serve.json")


def _req(port, method, path, body=None, timeout=30):
    import urllib.error
    import urllib.request

    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=(
            json.dumps(body).encode() if body is not None else None
        ),
    )
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _fleet_journal_entries(fleet):
    import glob

    out = []
    for path in sorted(
        glob.glob(os.path.join(fleet, "_serve_journal*.jsonl"))
    ):
        out.extend(_read_entries(path))
    return out


def _kill_all(procs):
    for p in procs.values():
        if p.poll() is None:
            p.kill()
        try:
            p.communicate(timeout=30)
        except ValueError:
            pass  # pipes already drained by an earlier communicate


@pytest.mark.faults
def test_sigkill_mid_job_finishes_on_survivor_identically(tmp_path):
    fleet = str(tmp_path / "fleet")
    procs, ports = {}, {}
    for rid in ("r1", "r2", "r3"):
        procs[rid] = _spawn_replica(
            fleet, str(tmp_path / f"wd_{rid}"), rid
        )
    try:
        for rid, p in procs.items():
            ports[rid] = _wait_port(str(tmp_path / f"wd_{rid}"), p)
        # max_neighbors=48 fattens every warm chunk several-fold:
        # with 12 micrographs at REPIC_CONSENSUS_CHUNK=1, the
        # window between "first artifact landed" and "job done" is
        # seconds wide, so the SIGKILL below lands mid-job even on
        # a heavily loaded CI machine (the raced-completion branch
        # retries with a replacement replica as a last resort)
        submit = {
            "in_dir": os.path.abspath(EXAMPLES),
            "box_size": 180,
            "options": {"use_mesh": False, "max_neighbors": 48},
        }
        jid = trace_id = runner = None
        for attempt in range(1, 4):
            port = ports[
                next(r for r, p in procs.items() if p.poll() is None)
            ]
            code, body = _req(port, "POST", "/v1/jobs", submit)
            assert code == 202, body
            jid = json.loads(body)["id"]
            trace_id = json.loads(body)["trace_id"]
            job_dir = os.path.join(fleet, "jobs", jid)
            runner = None
            deadline = time.time() + 180
            while time.time() < deadline:
                entries = _fleet_journal_entries(fleet)
                running = [
                    e for e in entries
                    if e.get("job") == jid
                    and e.get("state") == "running"
                ]
                boxed = os.path.isdir(job_dir) and any(
                    f.endswith(".box")
                    for f in os.listdir(job_dir)
                )
                if running and boxed:
                    runner = running[-1]["replica"]
                    break
                time.sleep(0.02)
            assert runner in procs, f"no replica ever ran {jid}"
            procs[runner].kill()  # SIGKILL: no drain, no release
            procs[runner].communicate()
            if not os.path.exists(
                os.path.join(fleet, f"_done.{jid}.json")
            ):
                break  # killed mid-job: no completion committed
            # the runner outran the kill (completed the job first):
            # replace the dead replica and try again
            assert attempt < 3, "never caught a replica mid-job"
            rid = f"r{attempt + 3}"
            procs.pop(runner)
            procs[rid] = _spawn_replica(
                fleet, str(tmp_path / f"wd_{rid}"), rid
            )
            ports[rid] = _wait_port(
                str(tmp_path / f"wd_{rid}"), procs[rid]
            )
        survivors = [r for r in procs if r != runner]
        # the job must finish on a survivor, SAME job id
        doc = None
        deadline = time.time() + 240
        while time.time() < deadline:
            code, body = _req(
                ports[survivors[0]], "GET", f"/v1/jobs/{jid}"
            )
            assert code == 200, body
            doc = json.loads(body)
            if doc["state"] in TERMINAL_STATES:
                break
            time.sleep(0.2)
        assert doc and doc["state"] == "finished", doc
        assert doc["id"] == jid
        assert doc["replica"] in survivors, doc
        assert doc["trace_id"] == trace_id
        # exactly one completion token, exactly one terminal record
        assert os.path.exists(
            os.path.join(fleet, f"_done.{jid}.json")
        )
        entries = _fleet_journal_entries(fleet)
        terminal = [
            e for e in entries
            if e.get("job") == jid
            and "event" not in e
            and e.get("state") in TERMINAL_STATES
        ]
        assert len(terminal) == 1, terminal
        assert terminal[0]["replica"] in survivors
        # the takeover is journaled: fence + lease steal provenance
        reassigned = [
            e for e in entries
            if e.get("event") == "job_reassigned"
            and e.get("job") == jid
        ]
        assert len(reassigned) == 1, reassigned
        assert reassigned[0]["from_replica"] == runner
        assert any(
            e.get("event") == "replica_fenced"
            and e.get("replica") == runner
            for e in entries
        )
        # one waterfall, two replicas: per-replica trace artifacts
        # carry the SAME accept-time trace id
        from repic_tpu.telemetry.trace import read_trace

        assert os.path.exists(
            os.path.join(job_dir, f"_trace.{runner}.jsonl")
        )
        assert os.path.exists(
            os.path.join(
                job_dir, f"_trace.{doc['replica']}.jsonl"
            )
        )
        recs = read_trace(job_dir)
        assert {r.get("trace") for r in recs} == {trace_id}
        # the survivor RESUMED (did not redo the dead replica's
        # chunks): both replicas' run journals contributed outcomes
        run_entries = []
        for r in (runner, doc["replica"]):
            run_entries.extend(
                _read_entries(
                    os.path.join(job_dir, f"_journal.{r}.jsonl")
                )
            )
        by_host = {
            e.get("host")
            for e in run_entries
            if e.get("status") == "ok"
        }
        assert by_host == {runner, doc["replica"]}, by_host
        # byte-identical artifacts: run the same input as a control
        # job on the (undisturbed) fleet and compare every BOX file
        code, body = _req(
            ports[survivors[0]], "POST", "/v1/jobs", submit
        )
        assert code == 202, body
        control = json.loads(body)["id"]
        deadline = time.time() + 240
        while time.time() < deadline:
            code, body = _req(
                ports[survivors[1]], "GET", f"/v1/jobs/{control}"
            )
            cdoc = json.loads(body)
            if cdoc["state"] in TERMINAL_STATES:
                break
            time.sleep(0.2)
        assert cdoc["state"] == "finished", cdoc
        control_dir = os.path.join(fleet, "jobs", control)
        names = sorted(
            f for f in os.listdir(control_dir)
            if f.endswith(".box")
        )
        assert len(names) == 12
        assert names == sorted(
            f for f in os.listdir(job_dir) if f.endswith(".box")
        )
        for name in names:
            with open(os.path.join(job_dir, name), "rb") as fa:
                a = fa.read()
            with open(os.path.join(control_dir, name), "rb") as fb:
                b = fb.read()
            assert a == b, f"artifact {name} differs after failover"
        # survivors drain clean: rc 0, zero orphaned leases
        for r in survivors:
            procs[r].send_signal(signal.SIGTERM)
        for r in survivors:
            out, _ = procs[r].communicate(timeout=120)
            assert procs[r].returncode == 0, out[-2000:]
        from repic_tpu.serve.fleet import FleetMember

        assert FleetMember(fleet, "probe").orphaned_leases() == []
    finally:
        _kill_all(procs)


@pytest.mark.faults
def test_replica_crash_fault_exits_25_and_survivor_finishes(
    tmp_path,
):
    """The deterministic twin of the SIGKILL test: only r1 carries
    the ``replica_crash`` plan, so it dies (``os._exit(25)`` — no
    lease release, no clean heartbeat) at its first chunk boundary;
    r2, started only after the crash, must fence r1, steal the
    lease, and finish the job — zero timing dependence anywhere."""
    from repic_tpu.serve.fleet import FLEET_CRASH_EXIT_CODE

    fleet = str(tmp_path / "fleet")
    procs = {}
    procs["r1"] = _spawn_replica(
        fleet,
        str(tmp_path / "wd_r1"),
        "r1",
        extra_env={"REPIC_TPU_FAULTS": "replica_crash:chunk:1"},
    )
    try:
        p1 = _wait_port(str(tmp_path / "wd_r1"), procs["r1"])
        submit = {
            "in_dir": os.path.abspath(FIXTURE),
            "box_size": 180,
            "options": {"use_mesh": False},
        }
        code, body = _req(p1, "POST", "/v1/jobs", submit)
        assert code == 202, body
        jid = json.loads(body)["id"]
        assert (
            procs["r1"].wait(timeout=180) == FLEET_CRASH_EXIT_CODE
        )
        procs["r1"].communicate()
        # the lease is still on disk, naming the dead replica
        lease = json.load(
            open(os.path.join(fleet, f"_joblease.{jid}.json"))
        )
        assert lease["replica"] == "r1"
        # the replacement replica runs its startup warmup: the dead
        # replica's compiled programs live in the SHARED fleet
        # compile cache, so r2 must replay them and start WARM
        procs["r2"] = _spawn_replica(
            fleet, str(tmp_path / "wd_r2"), "r2", warmup=True
        )
        p2 = _wait_port(str(tmp_path / "wd_r2"), procs["r2"])
        deadline = time.time() + 240
        doc = None
        while time.time() < deadline:
            code, body = _req(p2, "GET", f"/v1/jobs/{jid}")
            assert code == 200, body
            doc = json.loads(body)
            if doc["state"] in TERMINAL_STATES:
                break
            time.sleep(0.2)
        assert doc and doc["state"] == "finished", doc
        assert doc["replica"] == "r2"
        code, body = _req(p2, "GET", f"/v1/jobs/{jid}/artifacts")
        assert len(json.loads(body)["artifacts"]) == 3
        # the crash left exactly one completed chunk behind, and
        # the survivor's run RESUMED past it
        entries = _fleet_journal_entries(fleet)
        assert any(
            e.get("event") == "job_reassigned"
            and e.get("from_replica") == "r1"
            for e in entries
        )
        # ISSUE 13: the replacement started WARM — its warmup
        # replayed the crashed replica's recorded program(s) out of
        # the shared on-disk compile cache (persistent hit, not a
        # fresh compile of the serving program)
        warmups = [
            e for e in entries
            if e.get("event") == "warmup"
            and e.get("replica") == "r2"
        ]
        assert warmups, "r2 never journaled its warmup"
        assert warmups[-1]["programs_warmed"] >= 1, warmups[-1]
        assert warmups[-1]["persistent_cache_hits"] >= 1, (
            warmups[-1]
        )
    finally:
        _kill_all(procs)


@pytest.mark.faults
def test_poison_job_quarantined_within_budget_fleet(tmp_path):
    """The ISSUE 14 poison-pill acceptance gate, over real
    processes: a 3-replica fleet where ONE tenant's job carries a
    deterministic worker-killing input (``poison_job`` fault keyed
    on its in_dir, every attempt).  Without the budget the pill
    would serially kill every replica; with ``--reassign-budget 1``
    it kills at most budget+1 of them, the next fence winner
    commits terminal ``quarantined`` through the exactly-once
    token (≤ 1 reassignment, exactly one terminal record), at
    least one replica stays live — and the OTHER tenant's
    concurrent job completes with byte-identical artifacts vs an
    undisturbed control run, through a breaker the poison never
    opened.  ``--scheduler single`` keeps each replica holding one
    lease at a time, so the bystander job can never ride a
    poison-crashing worker's open set.
    """
    import shutil

    from repic_tpu.serve.jobs import TERMINAL_STATES as TS

    fleet = str(tmp_path / "fleet")
    # the poison input: a real, valid directory — only the fault
    # plan (keyed on the directory name) makes it lethal
    poison_dir = str(tmp_path / "poison_input")
    shutil.copytree(FIXTURE, poison_dir)
    tenants = tmp_path / "tenants.json"
    tenants.write_text(json.dumps({
        "tenants": [
            {"name": "teamA", "keys": ["ka"]},
            {"name": "teamB", "keys": ["kb"]},
        ]
    }))
    args = [
        "--scheduler", "single",
        "--reassign-budget", "1",
        "--tenants", str(tenants),
    ]
    env = {"REPIC_TPU_FAULTS": "poison_job:poison_input:inf"}
    procs, ports = {}, {}
    for rid in ("r1", "r2", "r3"):
        procs[rid] = _spawn_replica(
            fleet, str(tmp_path / f"wd_{rid}"), rid,
            extra_env=env, extra_args=args,
        )
    try:
        for rid, p in procs.items():
            ports[rid] = _wait_port(str(tmp_path / f"wd_{rid}"), p)

        def req_auth(port, method, path, body=None, key=None):
            import urllib.error
            import urllib.request

            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                method=method,
                data=(
                    json.dumps(body).encode()
                    if body is not None else None
                ),
                headers=(
                    {"Authorization": f"Bearer {key}"}
                    if key else {}
                ),
            )
            try:
                with urllib.request.urlopen(r, timeout=30) as resp:
                    return resp.status, resp.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        normal = {
            "in_dir": os.path.abspath(FIXTURE),
            "box_size": 180,
            "options": {"use_mesh": False},
        }
        poison = dict(normal, in_dir=os.path.abspath(poison_dir))
        # tenant B's innocent job AND tenant A's poison pill, in
        # flight concurrently on the same fleet
        code, body = req_auth(
            ports["r1"], "POST", "/v1/jobs", normal, key="kb"
        )
        assert code == 202, body
        b_jid = json.loads(body)["id"]
        import http.client

        p_jid = None
        try:
            code, body = req_auth(
                ports["r1"], "POST", "/v1/jobs", poison, key="ka"
            )
            assert code == 202, body
            p_jid = json.loads(body)["id"]
        except (http.client.HTTPException, OSError):
            # r1's own worker can claim the pill and die while the
            # 202 is in flight; the accept record is already
            # durable (journal-before-202), so read the id back
            deadline = time.time() + 60
            while p_jid is None and time.time() < deadline:
                for e in _fleet_journal_entries(fleet):
                    if (
                        e.get("state") == "queued"
                        and e.get("tenant") == "teamA"
                    ):
                        p_jid = e["job"]
                        break
                time.sleep(0.1)
            assert p_jid, "poison accept record never journaled"
        # wait for the quarantine token: the pill kills its first
        # runner, one survivor steals (reassignment #1) and dies,
        # the next fence winner quarantines instead of running
        done_path_ = os.path.join(fleet, f"_done.{p_jid}.json")
        deadline = time.time() + 300
        while time.time() < deadline:
            if os.path.exists(done_path_):
                break
            assert any(
                p.poll() is None for p in procs.values()
            ), "every replica died — the budget did not contain it"
            time.sleep(0.2)
        done = json.load(open(done_path_))
        assert done["state"] == "quarantined", done
        # blast radius bounded at budget+1 replicas; >= 1 live
        live = [r for r, p in procs.items() if p.poll() is None]
        dead = [r for r, p in procs.items() if p.poll() is not None]
        assert len(dead) <= 2, dead
        assert live, "no surviving replica"
        for r in dead:
            assert procs[r].returncode == 26, (  # poison exit code
                r, procs[r].returncode
            )
        port = ports[live[0]]
        # the survivor answers for the quarantined job — for its
        # OWNING tenant only
        code, body = req_auth(
            port, "GET", f"/v1/jobs/{p_jid}", key="ka"
        )
        assert code == 200, body
        doc = json.loads(body)
        assert doc["state"] == "quarantined", doc
        assert doc["tenant"] == "teamA"
        assert "retry budget" in doc["reason"]
        code, _ = req_auth(
            port, "GET", f"/v1/jobs/{p_jid}", key="kb"
        )
        assert code == 403
        # exactly one terminal record, <= budget reassignments
        entries = _fleet_journal_entries(fleet)
        terminal = [
            e for e in entries
            if e.get("job") == p_jid
            and "event" not in e and e.get("state") in TS
        ]
        assert len(terminal) == 1, terminal
        assert terminal[0]["state"] == "quarantined"
        reassigned = [
            e for e in entries
            if e.get("event") == "job_reassigned"
            and e.get("job") == p_jid
        ]
        assert len(reassigned) <= 1, reassigned
        # tenant B's concurrent job finished (reassigned if its
        # replica died mid-run — resume semantics hold)
        deadline = time.time() + 240
        while time.time() < deadline:
            code, body = req_auth(
                port, "GET", f"/v1/jobs/{b_jid}", key="kb"
            )
            assert code == 200, body
            bdoc = json.loads(body)
            if bdoc["state"] in TS:
                break
            time.sleep(0.2)
        assert bdoc["state"] == "finished", bdoc
        # the shared breaker never opened: a control job (same
        # input as B's) is accepted and completes...
        code, body = req_auth(
            port, "POST", "/v1/jobs", normal, key="kb"
        )
        assert code == 202, body  # 503 here = breaker poisoned
        c_jid = json.loads(body)["id"]
        deadline = time.time() + 240
        while time.time() < deadline:
            code, body = req_auth(
                port, "GET", f"/v1/jobs/{c_jid}", key="kb"
            )
            cdoc = json.loads(body)
            if cdoc["state"] in TS:
                break
            time.sleep(0.2)
        assert cdoc["state"] == "finished", cdoc
        # ...and B's failover-era artifacts are byte-identical to
        # the undisturbed control's
        b_dir = os.path.join(fleet, "jobs", b_jid)
        c_dir = os.path.join(fleet, "jobs", c_jid)
        names = sorted(
            f for f in os.listdir(c_dir) if f.endswith(".box")
        )
        assert names == sorted(
            f for f in os.listdir(b_dir) if f.endswith(".box")
        )
        assert names, "control produced no artifacts"
        for name in names:
            with open(os.path.join(b_dir, name), "rb") as fa, open(
                os.path.join(c_dir, name), "rb"
            ) as fb:
                assert fa.read() == fb.read(), (
                    f"artifact {name} differs for the bystander "
                    "tenant"
                )
    finally:
        _kill_all(procs)


@pytest.mark.faults
def test_fleet_drain_leaves_zero_orphaned_leases(tmp_path):
    """SIGTERM the whole fleet with work queued AND running: every
    replica exits rc 0, queued jobs stay journaled queued, and no
    lease survives without its completion token."""
    fleet = str(tmp_path / "fleet")
    procs, ports = {}, {}
    for rid in ("r1", "r2"):
        procs[rid] = _spawn_replica(
            fleet, str(tmp_path / f"wd_{rid}"), rid
        )
    try:
        for rid, p in procs.items():
            ports[rid] = _wait_port(str(tmp_path / f"wd_{rid}"), p)
        submit = {
            "in_dir": os.path.abspath(FIXTURE),
            "box_size": 180,
            "options": {"use_mesh": False},
        }
        ids = []
        for _ in range(3):
            code, body = _req(
                ports["r1"], "POST", "/v1/jobs", submit
            )
            assert code == 202, body
            ids.append(json.loads(body)["id"])
        for p in procs.values():
            p.send_signal(signal.SIGTERM)
        for rid, p in procs.items():
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0, (rid, out[-2000:])
        from repic_tpu.serve.fleet import FleetMember

        assert FleetMember(fleet, "probe").orphaned_leases() == []
        # every accepted job is either committed or still queued in
        # the durable journal for the next generation — none lost
        entries = _fleet_journal_entries(fleet)
        for jid in ids:
            states = [
                e.get("state")
                for e in entries
                if e.get("job") == jid and "event" not in e
            ]
            assert states, f"job {jid} vanished from the journal"
            terminal = states[-1] in TERMINAL_STATES
            assert terminal or states[-1] == "queued", states
    finally:
        _kill_all(procs)
