"""The fused ``consensus`` path's --multi_out / --get_cc flags must
produce byte-identical outputs to the two-phase ``get_cliques`` +
``run_ilp`` pipeline for the same flags and solver backend.

This closes the capability asymmetry where the reference's full
get_cliques flag surface (reference: repic/commands/
get_cliques.py:151-156,175-178 and run_ilp.py:93-119) existed only on
the slow two-phase compatibility path: the fused single-pass program
now writes the same multi-out TSVs (per-picker columns + confidence-0
singleton re-adds) and honors the largest-connected-component filter.

Equality holds exactly because the packing problem decomposes over
connected components (no constraint crosses a component boundary), so
the fused solve-everything-then-filter equals the two-phase
filter-then-solve; and the singleton re-add universe in run_ilp's TSV
is recoverable from the fused result's member indices.
"""

import os
import shutil
from types import SimpleNamespace

import pytest

from tests.conftest import REFERENCE_EXAMPLES, needs_reference

NAMES = (
    "Falcon_2012_06_12-14_33_35_0",
    "Falcon_2012_06_12-15_17_31_0",
)


def _stage_subset(tmp_path):
    stage = tmp_path / "in"
    for p in os.listdir(REFERENCE_EXAMPLES):
        src = os.path.join(REFERENCE_EXAMPLES, p)
        if not os.path.isdir(src):
            continue
        (stage / p).mkdir(parents=True)
        for n in NAMES:
            shutil.copy(os.path.join(src, n + ".box"), stage / p)
    return str(stage)


def _two_phase(tmp_path, in_dir, tag, *, multi_out, get_cc, backend):
    from repic_tpu.commands import get_cliques, run_ilp

    out = str(tmp_path / f"p_{tag}")
    get_cliques.main(
        SimpleNamespace(
            in_dir=in_dir,
            out_dir=out,
            box_size=180,
            multi_out=multi_out,
            get_cc=get_cc,
            max_neighbors=16,
            no_mesh=True,
        )
    )
    run_ilp.main(
        SimpleNamespace(
            in_dir=out, box_size=180, num_particles=None, backend=backend
        )
    )
    return out


@needs_reference
@pytest.mark.parametrize(
    "multi_out,get_cc,solver,use_mesh",
    [
        (True, False, "greedy", False),
        (False, True, "greedy", True),   # sharded over the CPU mesh
        (True, True, "greedy", False),
        (True, False, "lp", False),
        (False, True, "lp", False),
        (True, True, "lp", False),
    ],
)
def test_fused_flags_equal_two_phase(
    tmp_path, multi_out, get_cc, solver, use_mesh
):
    from repic_tpu.pipeline.consensus import run_consensus_dir

    in_dir = _stage_subset(tmp_path)
    tag = f"{int(multi_out)}{int(get_cc)}_{solver}"
    ref = _two_phase(
        tmp_path, in_dir, tag,
        multi_out=multi_out, get_cc=get_cc, backend=solver,
    )
    ours = str(tmp_path / f"f_{tag}")
    run_consensus_dir(
        in_dir,
        ours,
        180,
        multi_out=multi_out,
        get_cc=get_cc,
        solver=solver,
        use_mesh=use_mesh,
    )
    ext = ".tsv" if multi_out else ".box"
    for n in NAMES:
        with open(os.path.join(ref, n + ext)) as f:
            want = f.read()
        with open(os.path.join(ours, n + ext)) as f:
            got = f.read()
        assert got == want, f"{n}{ext} ({tag})"


def _write_box_dir(root, picker, name, rows):
    d = root / picker
    d.mkdir(parents=True, exist_ok=True)
    with open(d / (name + ".box"), "wt") as f:
        for x, y, s, c in rows:
            f.write(f"{x}\t{y}\t{s}\t{s}\t{c}\n")


@pytest.mark.parametrize("multi_out", [False, True])
def test_get_cc_empty_graph_micrograph(tmp_path, multi_out):
    """A micrograph with no above-threshold edge must produce an empty
    output under --get_cc, not crash on an empty largest-CC argmax
    (regression: largest_component_label on a node-less graph)."""
    from repic_tpu.pipeline.consensus import run_consensus_dir

    src = tmp_path / "in"
    # two pickers, one box each, far apart: zero overlap edges
    _write_box_dir(src, "a", "m0", [(10, 10, 180, 0.9)])
    _write_box_dir(src, "b", "m0", [(5000, 5000, 180, 0.8)])
    out = str(tmp_path / "out")
    stats = run_consensus_dir(
        str(src), out, 180,
        multi_out=multi_out, get_cc=True, use_mesh=False,
    )
    assert stats["particle_counts"] == {"m0": 0}
    if multi_out:
        with open(os.path.join(out, "m0.tsv")) as f:
            assert f.read() == "a\tb\n"
    else:
        assert os.path.getsize(os.path.join(out, "m0.box")) == 0


def test_cc_labels_use_per_picker_sizes():
    """Mixed-size ensembles: CC edges must be judged with the same
    per-picker box sizes as the clique graph.  A 100-px and a 20-px
    box at the same center have IoU 0.04 (< 0.3, no edge); a max-size
    scalar approximation would call it IoU 1.0 and invent an edge."""
    import jax.numpy as jnp
    import numpy as np

    from repic_tpu.ops.components import connected_component_labels

    xy = jnp.zeros((2, 1, 2), jnp.float32)
    mask = jnp.ones((2, 1), bool)
    _, node_mask = connected_component_labels(
        xy, mask, jnp.asarray([100.0, 20.0])
    )
    assert not bool(np.asarray(node_mask).any())
    _, node_mask = connected_component_labels(xy, mask, 100.0)
    assert bool(np.asarray(node_mask).all())
