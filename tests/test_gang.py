"""Gang supervision: watchdog, fault classification, re-formation.

The multi-process half of the gang story (a real SIGKILL mid-
collective) lives in tests/test_gang_chaos.py behind the capability
probe; everything here runs single-process — the supervisor's
protocol (census, election, epoch records, fencing, degrade) is
file-based and injectable, and the degenerate gang-of-one exercises
the REAL wiring through run_consensus_dir end to end.
"""

import json
import os
import time

import numpy as np
import pytest

from repic_tpu.parallel.gang import (
    GANG_CRASH_EXIT_CODE,
    GangConfig,
    GangError,
    GangFault,
    GangFenced,
    GangSupervisor,
    ServiceTimeEstimator,
    epoch_record_path,
    latest_epoch,
    member_path,
    read_epoch_record,
)
from repic_tpu.runtime import faults
from repic_tpu.runtime.cluster import (
    ClusterConfig,
    ClusterContext,
    fence_path,
    heartbeat_path,
)
from repic_tpu.runtime.journal import RunJournal, read_all_journals


# -- harness helpers --------------------------------------------------


def _write_heartbeat(coord_dir, host, rank, *, age_s=0.0):
    with open(heartbeat_path(coord_dir, host), "w") as f:
        json.dump(
            {
                "host": host,
                "rank": rank,
                "seq": 1,
                "ts": time.time() - age_s,
                "stopped": False,
            },
            f,
        )


def _write_member(coord_dir, host, rank):
    with open(member_path(coord_dir, host), "w") as f:
        json.dump(
            {
                "host": host,
                "rank": rank,
                "address": "127.0.0.1",
                "epoch": 1,
                "ts": time.time(),
            },
            f,
        )


def _supervisor(tmp_path, monkeypatch, *, rank=0, world=1,
                init_calls=None, **cfg_kw):
    """A bound supervisor over a tmp coordination dir with the JAX
    runtime stubbed out — the protocol under test is file-based."""
    monkeypatch.setenv("REPIC_TPU_HOST_ID", f"h{rank}")
    monkeypatch.setenv("REPIC_TPU_HOST_RANK", str(rank))
    monkeypatch.setenv("REPIC_TPU_NUM_HOSTS", str(world))
    cfg_kw.setdefault("host_timeout_s", 1.0)
    cfg_kw.setdefault("reform_timeout_s", 1.0)
    cfg = GangConfig(
        num_processes=world, process_id=rank, **cfg_kw
    )
    calls = init_calls if init_calls is not None else []
    sup = GangSupervisor(
        cfg,
        str(tmp_path),
        init_runtime=lambda coord, w, r, t: calls.append(
            (coord, w, r)
        ),
        shutdown_runtime=lambda: True,
    )
    sup.epoch = 1
    sup.mode = "gang"
    ctx = ClusterContext(
        ClusterConfig(
            coordination_dir=str(tmp_path),
            heartbeat_interval_s=0.2,
            host_timeout_s=1.0,
        ),
        str(tmp_path),
    )
    journal = RunJournal.open(
        str(tmp_path), {"run": "gang-test"},
        host=ctx.host, cluster=True,
    )
    ctx.beat()  # one renewal, no thread — deterministic liveness
    sup.bind(journal, ctx)
    return sup, journal


# -- estimator --------------------------------------------------------


def test_service_time_estimator_decay_and_deadline():
    cfg = GangConfig(
        watchdog_factor=3.0, watchdog_floor_s=5.0,
        first_deadline_s=100.0,
    )
    est = ServiceTimeEstimator(alpha=0.5)
    # no estimate / fresh compile -> the generous first deadline
    assert est.deadline(cfg) == 100.0
    est.observe(10.0)
    assert est.deadline(cfg, fresh_compile=True) == 100.0
    assert est.deadline(cfg) == pytest.approx(30.0)
    # decays toward the recent service time (never below the floor)
    est.observe(0.0)
    assert est.deadline(cfg) == pytest.approx(15.0)
    for _ in range(20):
        est.observe(0.0)
    assert est.deadline(cfg) == 5.0


def test_gang_config_validation():
    with pytest.raises(ValueError):
        GangConfig(watchdog_factor=0.5)
    with pytest.raises(ValueError):
        GangConfig(min_world=0)


# -- watchdog classification ------------------------------------------


@pytest.mark.faults
def test_watchdog_dead_peer_is_gang_fault(tmp_path, monkeypatch):
    """A stuck dispatch plus a heartbeat-dead peer classifies as a
    gang fault (kind=peer_dead naming the peer) — never a slow
    chunk."""
    sup, _ = _supervisor(
        tmp_path, monkeypatch, rank=0, world=2,
        watchdog_floor_s=0.2, first_deadline_s=0.2,
        max_extensions=5,
    )
    _write_member(tmp_path, "h1", 1)
    _write_heartbeat(tmp_path, "h1", 1, age_s=60.0)  # long dead
    with pytest.raises(GangFault) as ei:
        sup.dispatch(lambda: time.sleep(30.0), key="chunk:0")
    assert ei.value.kind == "peer_dead"
    assert ei.value.dead == ("h1",)


@pytest.mark.faults
def test_watchdog_all_live_extends_then_stall_fault(
    tmp_path, monkeypatch
):
    """Every peer live -> the deadline extends (slow chunk), and only
    after the bounded extensions is the stall itself a fault."""
    sup, _ = _supervisor(
        tmp_path, monkeypatch, rank=0, world=2,
        watchdog_floor_s=0.2, first_deadline_s=0.2,
        max_extensions=2,
    )
    _write_member(tmp_path, "h1", 1)
    _write_heartbeat(tmp_path, "h1", 1, age_s=0.0)  # live peer
    t0 = time.monotonic()
    with pytest.raises(GangFault) as ei:
        sup.dispatch(lambda: time.sleep(30.0), key="chunk:0")
    assert ei.value.kind == "stall"
    # 1 base deadline + 2 extensions before the fault
    assert time.monotonic() - t0 >= 0.55


@pytest.mark.faults
def test_watchdog_completion_observes_service_time(
    tmp_path, monkeypatch
):
    sup, _ = _supervisor(tmp_path, monkeypatch)
    assert sup.dispatch(lambda: 41 + 1, key="chunk:0") == 42
    assert sup.estimator.ema is not None


@pytest.mark.faults
def test_dispatch_exceptions_propagate_unchanged(
    tmp_path, monkeypatch
):
    """Ordinary errors belong to the caller's retry ladder, not the
    gang machinery."""
    sup, _ = _supervisor(tmp_path, monkeypatch)

    def _boom():
        raise ValueError("data error")

    with pytest.raises(ValueError, match="data error"):
        sup.dispatch(_boom, key="chunk:0")


@pytest.mark.faults
def test_coordinator_loss_fault_site(tmp_path, monkeypatch):
    sup, _ = _supervisor(
        tmp_path, monkeypatch,
        watchdog_floor_s=5.0, first_deadline_s=5.0,
    )
    with faults.fault_plan("coordinator_loss"):
        t0 = time.monotonic()
        with pytest.raises(GangFault) as ei:
            sup.dispatch(lambda: time.sleep(30.0), key="chunk:0")
    assert ei.value.kind == "coordinator_loss"
    assert time.monotonic() - t0 < 5.0  # fired before the deadline


# -- re-formation protocol --------------------------------------------


@pytest.mark.faults
def test_reform_survivor_becomes_leader_and_fences_dead(
    tmp_path, monkeypatch
):
    """Lowest-rank survivor publishes the epoch record (todo +
    members + world), dead members get cluster fences, and the
    transition journals gang_reformed."""
    calls = []
    sup, journal = _supervisor(
        tmp_path, monkeypatch, rank=1, world=2, init_calls=calls
    )
    _write_member(tmp_path, "h0", 0)
    _write_heartbeat(tmp_path, "h0", 0, age_s=60.0)  # dead leader
    mode = sup.reform(["m2", "m3"], chunk=8)
    assert mode == "gang"
    assert sup.epoch == 2 and sup.world == 1 and sup.rank == 0
    assert calls == []  # world of one: no distributed re-init
    rec = read_epoch_record(tmp_path, 2)
    assert rec["members"] == {"h1": 0}
    assert rec["todo"] == ["m2", "m3"]
    assert rec["chunk"] == 8
    assert os.path.exists(fence_path(tmp_path, "h0"))
    events = [
        e["event"]
        for e in read_all_journals(str(tmp_path))
        if "event" in e
    ]
    assert "gang_reformed" in events
    assert "host_fenced" in events


@pytest.mark.faults
def test_reform_follower_adopts_leader_record(
    tmp_path, monkeypatch
):
    """A surviving non-leader waits for the record and re-initializes
    at its new rank against the published coordinator."""
    calls = []
    sup, _ = _supervisor(
        tmp_path, monkeypatch, rank=1, world=3, init_calls=calls
    )
    _write_member(tmp_path, "h0", 0)
    _write_heartbeat(tmp_path, "h0", 0, age_s=0.0)  # live leader
    with open(epoch_record_path(tmp_path, 2), "w") as f:
        json.dump(
            {
                "epoch": 2,
                "world": 2,
                "coordinator": "127.0.0.1:7811",
                "members": {"h0": 0, "h1": 1},
                "todo": ["m5"],
                "chunk": 4,
            },
            f,
        )
    mode = sup.reform(["m5"], chunk=4)
    assert mode == "gang"
    assert sup.epoch == 2 and sup.world == 2 and sup.rank == 1
    assert calls == [("127.0.0.1:7811", 2, 1)]
    assert sup.current_todo() == ["m5"]
    assert sup.current_chunk() == 4


@pytest.mark.faults
def test_reform_excluded_host_is_fenced(tmp_path, monkeypatch):
    """A host the new gang presumed dead must STOP (its late writes
    lose by epoch), not rejoin."""
    sup, _ = _supervisor(tmp_path, monkeypatch, rank=1, world=2)
    _write_member(tmp_path, "h0", 0)
    _write_heartbeat(tmp_path, "h0", 0, age_s=0.0)
    with open(epoch_record_path(tmp_path, 2), "w") as f:
        json.dump(
            {
                "epoch": 2,
                "world": 1,
                "coordinator": None,
                "members": {"h0": 0},  # h1 presumed dead
                "todo": [],
            },
            f,
        )
    with pytest.raises(GangFenced):
        sup.reform([], chunk=4)


@pytest.mark.faults
def test_reform_below_min_world_degrades(tmp_path, monkeypatch):
    sup, journal = _supervisor(
        tmp_path, monkeypatch, rank=0, world=2, min_world=2
    )
    _write_member(tmp_path, "h1", 1)
    _write_heartbeat(tmp_path, "h1", 1, age_s=60.0)  # dead peer
    mode = sup.reform(["m1"], chunk=8)
    assert mode == "independent"
    assert sup.mode == "independent"
    events = [
        e["event"]
        for e in read_all_journals(str(tmp_path))
        if "event" in e
    ]
    assert "gang_degraded" in events


@pytest.mark.faults
def test_reform_no_degrade_raises(tmp_path, monkeypatch):
    sup, _ = _supervisor(
        tmp_path, monkeypatch, rank=0, world=2,
        min_world=2, allow_degrade=False,
    )
    _write_member(tmp_path, "h1", 1)
    _write_heartbeat(tmp_path, "h1", 1, age_s=60.0)
    with pytest.raises(GangError):
        sup.reform(["m1"], chunk=8)


@pytest.mark.faults
def test_reform_halves_chunk_on_oom_fault(tmp_path, monkeypatch):
    """The chunk size is part of the epoch record (a gang-wide
    decision): an OOM-flagged gang fault halves it for the re-formed
    gang."""
    sup, _ = _supervisor(tmp_path, monkeypatch)
    sup.record_fault(
        GangFault("oom", kind="dispatch_error", oom=True),
        chunk=16, context="test",
    )
    mode = sup.reform(["m1"], chunk=16)
    assert mode == "gang"
    assert sup.current_chunk() == 8


def test_independent_share_splits_by_census(tmp_path, monkeypatch):
    sup, _ = _supervisor(tmp_path, monkeypatch, rank=1, world=2)
    _write_member(tmp_path, "h0", 0)
    _write_heartbeat(tmp_path, "h0", 0, age_s=0.0)
    names = [f"m{i}" for i in range(10)]
    share = sup.independent_share(names)
    assert share == names[5:]  # census index 1 of 2


def test_latest_epoch_scan(tmp_path):
    assert latest_epoch(str(tmp_path)) == 0
    for e in (1, 3):
        with open(epoch_record_path(tmp_path, e), "w") as f:
            json.dump({"epoch": e}, f)
    assert latest_epoch(str(tmp_path)) == 3


def test_relaunch_outranks_dead_generation(tmp_path, monkeypatch):
    """A relaunched gang run over a coordination directory holding a
    dead generation's epoch records and member files must form ABOVE
    them: its records win the merged fold, and the stale members
    never read as heartbeat-dead peers."""
    # leftovers of a previous generation that reached epoch 3
    with open(epoch_record_path(tmp_path, 3), "w") as f:
        json.dump({"epoch": 3, "members": {"old0": 0}}, f)
    _write_member(tmp_path, "old0", 0)
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    sup = GangSupervisor(GangConfig(), str(tmp_path))
    sup.form_runtime()
    assert sup.epoch == 4
    # the stale member record predates this formation: excluded
    sup.host = "h0"
    assert "old0" not in sup.members()
    assert sup.dead_peers() == []


# -- epoch write-fencing in the merged journal fold -------------------


@pytest.mark.faults
def test_merged_fold_stale_epoch_straggler_loses(tmp_path):
    """A fenced straggler's LATE write (newer timestamp, older gang
    epoch) loses the merged last-writer-wins fold to the re-formed
    gang's record."""
    from repic_tpu.runtime.journal import merged_latest

    j_survivor = RunJournal.open(
        str(tmp_path), {"run": "x"}, host="hB", cluster=True
    )
    j_straggler = RunJournal.open(
        str(tmp_path), {"run": "x"}, host="hA", cluster=True
    )
    j_survivor.record(
        "m1", "ok", gang_epoch=2, particles=7
    )
    time.sleep(0.02)  # straggler writes strictly LATER
    j_straggler.record(
        "m1", "ok", gang_epoch=1, particles=99
    )
    merged = merged_latest(str(tmp_path))
    assert merged["m1"]["particles"] == 7
    assert merged["m1"]["gang_epoch"] == 2
    # non-gang records (no epoch field) still fold by timestamp
    j_survivor.record("m2", "ok", particles=1)
    time.sleep(0.02)
    j_straggler.record("m2", "ok", particles=2)
    assert merged_latest(str(tmp_path))["m2"]["particles"] == 2
    # and a LATER non-gang record overrides gang records by
    # timestamp (a plain --resume over a former gang directory is a
    # newer run, not a straggler — epoch fencing applies only
    # between two gang records)
    time.sleep(0.02)
    j_survivor.record("m1", "ok", particles=3)
    assert merged_latest(str(tmp_path))["m1"]["particles"] == 3


# -- fault-site plumbing ----------------------------------------------


def test_gang_fault_sites_registered():
    for site in (
        "gang_peer_crash", "gang_peer_stall", "coordinator_loss"
    ):
        assert site in faults.KNOWN_SITES
    assert GANG_CRASH_EXIT_CODE == 27


# -- satellite: empty shards / pad-participate ------------------------


def test_shard_for_process_high_ranks_empty():
    from repic_tpu.parallel import distributed

    items = ["a", "b", "c"]
    shards = [
        distributed.shard_for_process(
            items, process_id=i, process_count=5
        )
        for i in range(5)
    ]
    assert [x for s in shards for x in s] == items
    assert shards[3] == [] and shards[4] == []


def test_local_row_quota_floors_at_device_count():
    from repic_tpu.parallel.distributed import local_row_quota

    assert local_row_quota(0, 4) == 4   # empty shard participates
    assert local_row_quota(1, 4) == 4
    assert local_row_quota(5, 4) == 8
    assert local_row_quota(8, 4) == 8


def test_pad_batch_empty_shard_is_all_padding():
    from repic_tpu.parallel.batching import pad_batch

    batch = pad_batch(
        [], pad_micrographs_to=8, capacity=64, num_pickers=3
    )
    assert batch.xy.shape == (8, 3, 64, 2)
    assert batch.num_micrographs == 0
    assert not batch.mask.any()
    assert batch.names == ("",) * 8
    with pytest.raises(ValueError, match="num_pickers"):
        pad_batch([], pad_micrographs_to=8)


def test_assemble_global_batch_pads_short_and_empty_shards():
    from repic_tpu.parallel import distributed
    from repic_tpu.parallel.mesh import consensus_mesh

    mesh = consensus_mesh()
    n_dev = len(mesh.devices.reshape(-1))
    short = np.ones((n_dev - 2, 3), np.float32)
    empty = np.zeros((0, 3), np.float32)
    g_short, g_empty = distributed.assemble_global_batch(
        mesh, (short, empty), pad_rows_to=n_dev
    )
    assert g_short.shape == (n_dev, 3)
    assert g_empty.shape == (n_dev, 3)
    np.testing.assert_array_equal(
        np.asarray(g_short)[: n_dev - 2], short
    )
    assert not np.asarray(g_short)[n_dev - 2:].any()
    assert not np.asarray(g_empty).any()


# -- satellite: structured env / identity failures --------------------


def test_initialize_garbage_env_is_structured_error(monkeypatch):
    from repic_tpu.parallel import distributed

    monkeypatch.setenv("JAX_NUM_PROCESSES", "banana")
    with pytest.raises(
        ValueError, match="JAX_NUM_PROCESSES='banana'"
    ):
        distributed.initialize()
    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    monkeypatch.setenv("JAX_PROCESS_ID", "0.5")
    with pytest.raises(ValueError, match="JAX_PROCESS_ID='0.5'"):
        distributed.initialize()


def test_gang_supervisor_garbage_env_is_structured_error(
    tmp_path, monkeypatch
):
    """The supervisor parses the launch env BEFORE initialize runs —
    the same structured one-line error applies there."""
    monkeypatch.setenv("JAX_NUM_PROCESSES", "$(NPROC)")
    with pytest.raises(
        ValueError, match="JAX_NUM_PROCESSES='\\$\\(NPROC\\)'"
    ):
        GangSupervisor(GangConfig(), str(tmp_path))


def test_runtime_identity_warns_on_private_module_drift(
    monkeypatch,
):
    """The narrowed except must WARN (structured, same shape as the
    initialize() fallbacks) instead of silently reporting
    single-host."""
    import sys

    import jax._src as jax_src

    from repic_tpu.parallel import distributed

    monkeypatch.delattr(jax_src, "distributed")
    monkeypatch.setitem(sys.modules, "jax._src.distributed", None)
    with pytest.warns(
        RuntimeWarning, match="no-runtime-identity"
    ):
        assert distributed.runtime_identity() is None


# -- end-to-end: the degenerate gang of one through the real wiring --


FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures", "mini10017",
)


def test_gang_of_one_end_to_end_byte_identical(tmp_path):
    """run_consensus_dir(gang=...) with world 1 exercises the REAL
    gang path (shard_for_process, assemble_global_batch, watchdog,
    epoch-tagged journal) and must produce byte-identical BOX files
    vs the plain run."""
    from repic_tpu.pipeline.consensus import run_consensus_dir

    plain = tmp_path / "plain"
    gang = tmp_path / "gang"
    run_consensus_dir(FIXTURE, str(plain), 180, use_mesh=False)
    stats = run_consensus_dir(
        FIXTURE, str(gang), 180, gang=GangConfig()
    )
    assert stats["journal"] == {"ok": 3}
    assert stats["gang"]["mode"] == "gang"
    assert stats["gang"]["epoch"] == 1
    boxes = sorted(
        f for f in os.listdir(plain) if f.endswith(".box")
    )
    assert boxes
    for f in boxes:
        assert (gang / f).read_text() == (plain / f).read_text()
    events = [
        e["event"]
        for e in read_all_journals(str(gang))
        if "event" in e
    ]
    assert "gang_formed" in events
    merged = {
        e["name"]: e
        for e in read_all_journals(str(gang))
        if "name" in e
    }
    assert all(e.get("gang_epoch") == 1 for e in merged.values())


@pytest.mark.faults
def test_gang_stall_fault_reforms_and_completes(tmp_path):
    """A wedged dispatch (gang_peer_stall) under a tight watchdog:
    the fault is classified, the gang re-forms at epoch 2 over the
    remaining todo, the run completes with zero lost micrographs,
    and the journal shows the gang_fault -> gang_reformed
    sequence."""
    from repic_tpu.pipeline.consensus import run_consensus_dir

    out = tmp_path / "out"
    with faults.fault_plan("gang_peer_stall:gchunk:1"):
        stats = run_consensus_dir(
            FIXTURE, str(out), 180,
            gang=GangConfig(
                watchdog_factor=2.0,
                watchdog_floor_s=0.3,
                first_deadline_s=0.5,
                max_extensions=1,
                reform_timeout_s=5.0,
            ),
        )
    assert stats["journal"] == {"ok": 3}
    # at least the injected stall fault fired (a slow compile under
    # the tight test deadline may legitimately add another fault +
    # re-formation round — the invariants, not the count, are the
    # contract: every fault re-formed, nothing degraded, epoch
    # advanced once per re-formation)
    assert stats["gang"]["faults"] >= 1
    assert stats["gang"]["reformations"] == stats["gang"]["faults"]
    assert stats["gang"]["epoch"] == 1 + stats["gang"]["reformations"]
    assert stats["gang"]["mode"] == "gang"
    seq = [
        (e["event"], e.get("kind"))
        for e in read_all_journals(str(out))
        if e.get("event", "").startswith("gang")
    ]
    assert seq[0] == ("gang_formed", None)
    assert ("gang_fault", "stall") in seq
    # strict alternation: every fault is followed by a re-formation
    assert seq[1:] == [
        pair
        for _ in range(stats["gang"]["faults"])
        for pair in (("gang_fault", "stall"), ("gang_reformed", None))
    ]
    # exactly one terminal record per micrograph, all epoch-tagged
    names = [
        e["name"]
        for e in read_all_journals(str(out))
        if "name" in e
    ]
    assert sorted(names) == sorted(set(names))


@pytest.mark.faults
def test_gang_fault_budget_degrades_to_independent(tmp_path):
    """A spent fault budget degrades the gang to independent
    per-host execution, which still finishes the run (the lenient
    ladder owns the remainder) — and the journal shows the
    gang_degraded transition with a bumped epoch."""
    from repic_tpu.pipeline.consensus import run_consensus_dir

    out = tmp_path / "out"
    with faults.fault_plan("gang_peer_stall:gchunk:1"):
        stats = run_consensus_dir(
            FIXTURE, str(out), 180,
            gang=GangConfig(
                watchdog_factor=2.0,
                watchdog_floor_s=0.3,
                first_deadline_s=0.5,
                max_extensions=1,
                reform_timeout_s=5.0,
                max_faults=0,
            ),
        )
    assert stats["journal"] == {"ok": 3}
    assert stats["gang"]["mode"] == "independent"
    events = [
        e
        for e in read_all_journals(str(out))
        if e.get("event", "").startswith("gang")
    ]
    kinds = [e["event"] for e in events]
    assert kinds == ["gang_formed", "gang_fault", "gang_degraded"]
    # degraded records carry the bumped epoch: stragglers lose
    records = {
        e["name"]: e
        for e in read_all_journals(str(out))
        if "name" in e
    }
    assert all(
        r.get("gang_epoch") == 2 for r in records.values()
    ), records


# -- golden membership parity: gang chunk entry vs single -------------


def test_gang_chunk_entry_matches_unsharded(rng):
    """The @checked gang chunk entry over the mesh must reproduce the
    unsharded program's picks exactly (same membership, same
    weights)."""
    import jax

    from repic_tpu.parallel.mesh import consensus_mesh
    from repic_tpu.pipeline.consensus import (
        gang_consensus_chunk,
        make_batched_consensus,
    )

    m, k, n = 8, 3, 32
    xy = rng.uniform(50, 900, size=(m, k, n, 2)).astype(np.float32)
    conf = rng.uniform(0.05, 1.0, size=(m, k, n)).astype(np.float32)
    mask = np.ones((m, k, n), bool)
    mesh = consensus_mesh()
    res_gang = gang_consensus_chunk(
        xy, conf, mask, 180.0,
        max_neighbors=8, clique_capacity=128, mesh=mesh,
    )
    ref = make_batched_consensus(
        max_neighbors=8, clique_capacity=128
    )(xy, conf, mask, 180.0)
    jax.block_until_ready(res_gang.picked)
    np.testing.assert_array_equal(
        np.asarray(res_gang.picked), np.asarray(ref.picked)
    )
    np.testing.assert_array_equal(
        np.asarray(res_gang.member_idx)[np.asarray(res_gang.valid)],
        np.asarray(ref.member_idx)[np.asarray(ref.valid)],
    )
    np.testing.assert_allclose(
        np.asarray(res_gang.w), np.asarray(ref.w), rtol=1e-6
    )
