"""Gang chaos: SIGKILL-equivalent peer loss mid-collective.

The acceptance gate of the pod-scale gang path (docs/robustness.md
"Pod-scale gangs"): three real ``jax.distributed`` worker processes
execute one gang-scheduled consensus run; the victim dies via the
``gang_peer_crash`` fault site (``os._exit`` as a chunk's collective
launches — SIGKILL semantics: no journal close, no heartbeat stop,
survivors blocked inside the program).  The survivors' watchdogs must
classify the gang fault, fence the victim, re-form a two-host gang,
resume from the merged journals, and produce BOX artifacts
byte-identical to an uninterrupted single-process control run with
zero lost and zero duplicated micrographs.

Gated by the multiprocess capability probe (the sandbox CPU backend
cannot run cross-process SPMD; the probe skips with the backend's own
reason there and runs the test for real anywhere it can).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from repic_tpu.parallel.gang import GANG_CRASH_EXIT_CODE
from repic_tpu.runtime.journal import (
    DONE_STATUSES,
    read_all_journals,
)

WORLD = 3
MICROGRAPHS = 9
PICKERS = ("alpha", "beta", "gamma")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _make_inputs(root) -> str:
    """Deterministic multi-chunk workload: 3 pickers x 9 micrographs
    of ~24 particles each (small enough for 1-device workers, large
    enough that the injected crash leaves real work to resume)."""
    rng = np.random.default_rng(7)
    in_dir = os.path.join(root, "inputs")
    for picker in PICKERS:
        os.makedirs(os.path.join(in_dir, picker), exist_ok=True)
    for m in range(MICROGRAPHS):
        base = rng.uniform(80, 880, size=(24, 2))
        for picker in PICKERS:
            jitter = rng.uniform(-6, 6, size=base.shape)
            conf = rng.uniform(0.1, 1.0, size=len(base))
            rows = [
                f"{x - 90:.2f}\t{y - 90:.2f}\t180\t180\t{c:.4f}"
                for (x, y), c in zip(base + jitter, conf)
            ]
            path = os.path.join(
                in_dir, picker, f"mic_{m:03d}.box"
            )
            with open(path, "w") as f:
                f.write("\n".join(rows) + "\n")
    return in_dir


def _spawn_worker(repo_root, in_dir, out_dir, *, port, rank,
                  extra_env=None):
    env = dict(os.environ)
    env.update(
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        JAX_NUM_PROCESSES=str(WORLD),
        JAX_PROCESS_ID=str(rank),
        REPIC_TPU_HOST_ID=f"gw{rank}",
        REPIC_TPU_HOST_RANK=str(rank),
        REPIC_TPU_NUM_HOSTS=str(WORLD),
        PYTHONPATH=repo_root
        + os.pathsep
        + env.get("PYTHONPATH", ""),
    )
    env.update(extra_env or {})
    return subprocess.Popen(
        [
            sys.executable,
            os.path.join(
                os.path.dirname(__file__), "gang_worker.py"
            ),
            in_dir,
            out_dir,
            "180",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.usefixtures("multiprocess_backend")
def test_gang_survives_peer_killed_mid_collective(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(__file__))
    in_dir = _make_inputs(str(tmp_path))

    # Uninterrupted single-process control run: the byte-identity
    # reference.  (Same config surface the gang run journals.)
    control = os.path.join(str(tmp_path), "control")
    proc = subprocess.run(
        [
            sys.executable, "-c",
            "import sys; from repic_tpu.pipeline.consensus import "
            "run_consensus_dir; run_consensus_dir(sys.argv[1], "
            "sys.argv[2], 180, use_mesh=False)",
            in_dir, control,
        ],
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "REPIC_TPU_NO_CACHE": "1",
            "PYTHONPATH": repo_root
            + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        },
        capture_output=True,
        text=True,
        timeout=420,
        cwd=repo_root,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[
        -2000:
    ]

    # Chaos run: rank 2 dies as the SECOND chunk's collective
    # launches (fault key `gchunk:1:1` = epoch 1, chunk index 1 —
    # after journaling its chunk-0 share, so the survivors must
    # both resume completed work and recover the remainder).
    out_dir = os.path.join(str(tmp_path), "gang_out")
    os.makedirs(out_dir, exist_ok=True)
    port = _free_port()
    workers = []
    for rank in range(WORLD):
        extra = (
            {"REPIC_TPU_FAULTS": "gang_peer_crash:gchunk:1:1:1"}
            if rank == 2
            else {}
        )
        workers.append(
            _spawn_worker(
                repo_root, in_dir, out_dir,
                port=port, rank=rank, extra_env=extra,
            )
        )
    outs = []
    for w in workers:
        try:
            out, _ = w.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for x in workers:
                if x.poll() is None:
                    x.kill()
            out, _ = w.communicate()
            out = (out or "") + "\n[chaos timeout]"
        outs.append(out or "")

    # the victim died from the injected crash, both survivors
    # finished the run
    assert workers[2].returncode == GANG_CRASH_EXIT_CODE, outs[2][
        -3000:
    ]
    for rank in (0, 1):
        assert workers[rank].returncode == 0, (
            f"survivor {rank} failed:\n{outs[rank][-3000:]}"
        )

    # the journaled transition: fault -> re-formation at world 2
    events = [
        e for e in read_all_journals(out_dir) if "event" in e
    ]
    kinds = [e["event"] for e in events]
    assert "gang_fault" in kinds, kinds
    reformed = [e for e in events if e["event"] == "gang_reformed"]
    assert reformed and all(
        e["world"] == WORLD - 1 for e in reformed
    ), reformed

    # zero lost, zero duplicated: exactly one terminal record per
    # micrograph in the epoch-aware merged fold, all ok
    merged: dict = {}
    for e in read_all_journals(out_dir):
        if "name" in e:
            merged[e["name"]] = e
    names = sorted(merged)
    assert names == sorted(
        f"mic_{m:03d}" for m in range(MICROGRAPHS)
    )
    assert all(
        merged[n]["status"] in DONE_STATUSES for n in names
    ), {n: merged[n]["status"] for n in names}

    # byte-identical artifacts vs the uninterrupted control
    control_boxes = sorted(
        f for f in os.listdir(control) if f.endswith(".box")
    )
    assert len(control_boxes) == MICROGRAPHS
    for f in control_boxes:
        got = open(os.path.join(out_dir, f)).read()
        want = open(os.path.join(control, f)).read()
        assert got == want, f"artifact drift in {f}"

    # every surviving host reported the re-formed gang in its stats
    for rank in (0, 1):
        stats = json.load(
            open(os.path.join(out_dir, f"stats.gw{rank}.json"))
        )
        assert stats["gang"]["mode"] in ("gang", "independent")
        assert stats["gang"]["faults"] >= 1
