"""Particle-axis (stripe) sharded consensus vs the single-device path.

The giant-micrograph path shards ONE micrograph's particles into
device-owned x-stripes with a box-size halo (pipeline/giant.py — the
framework's sequence-parallel analog).  Gates:

* the stripe-sharded clique set and the single-device clique set are
  IDENTICAL (membership and weights) on both the dense and bucketed
  enumeration paths, over the 8-device CPU mesh;
* the globally-solved consensus equals the single-device consensus
  (same picked member sets — the global solve is what makes
  cross-stripe halo conflicts safe);
* anchors are never double-owned and halo construction misses no
  boundary clique (stripe count sweep).
"""

import numpy as np
import pytest

from repic_tpu.parallel.batching import pad_batch
from repic_tpu.pipeline.consensus import run_consensus_batch
from repic_tpu.pipeline.giant import build_stripes, run_consensus_giant
from repic_tpu.utils.box_io import BoxSet

BOX = 180.0


def _field(n, k=3, seed=0, spacing=150.0, jitter=12.0):
    """Cluster-structured dense field, one BoxSet per picker."""
    rng = np.random.default_rng(seed)
    side = int(np.ceil(np.sqrt(n)))
    gx, gy = np.meshgrid(np.arange(side), np.arange(side))
    base = (
        np.stack([gx, gy], -1).reshape(-1, 2)[:n].astype(np.float32)
        * spacing
        + spacing
    )
    sets = []
    for _ in range(k):
        xy = base + rng.normal(0, jitter, base.shape).astype(np.float32)
        conf = rng.uniform(0.05, 1.0, size=n).astype(np.float32)
        wh = np.full((n, 2), BOX, np.float32)
        sets.append(BoxSet(xy=xy, conf=conf, wh=wh))
    return sets


def _single_device_result(sets, spatial):
    batch = pad_batch([("m0", sets)], pad_micrographs_to=1)
    res = run_consensus_batch(
        batch, BOX, use_mesh=False, spatial=spatial
    )
    valid = np.asarray(res.valid[0])
    return (
        np.asarray(res.member_idx[0])[valid],
        np.asarray(res.w[0])[valid],
        np.asarray(res.picked[0])[valid],
    )


def _keys(member, k):
    """One hashable identity per clique row."""
    return [
        tuple((p, int(row[p])) for p in range(k)) for row in member
    ]


def _clique_keys(member, k):
    return set(_keys(member, k))


@pytest.mark.parametrize(
    "n,spatial", [(1200, False), (5200, True)],
    ids=["dense", "bucketed"],
)
def test_striped_equals_single_device(n, spatial):
    sets = _field(n)
    k = len(sets)
    giant = run_consensus_giant(
        sets, BOX, use_mesh=True, spatial=spatial
    )
    assert giant["n_stripes"] >= 8  # really sharded over the mesh

    g_valid = giant["valid"]
    g_member = giant["member_idx"][g_valid]
    g_w = dict(zip(_keys(g_member, k), giant["w"][g_valid]))

    s_member, s_w, s_picked = _single_device_result(sets, spatial)
    want = _clique_keys(s_member, k)
    got = _clique_keys(g_member, k)
    assert got == want  # identical clique sets across stripes

    for key, wv in zip(_keys(s_member, k), s_w):
        np.testing.assert_allclose(g_w[key], wv, atol=1e-5)

    # consensus equality: same picked member sets
    g_picked_keys = _clique_keys(
        giant["member_idx"][giant["picked"]], k
    )
    s_picked_keys = _clique_keys(s_member[s_picked], k)
    assert g_picked_keys == s_picked_keys


def test_anchors_owned_exactly_once():
    sets = _field(900, seed=3)
    xy, conf, mask, l2g = build_stripes(sets, 8, BOX)
    owned = l2g[:, 0, :][mask[:, 0, :]]
    assert len(owned) == sets[0].n
    assert len(np.unique(owned)) == sets[0].n


@pytest.mark.parametrize("n_stripes", [1, 3, 8, 16])
def test_stripe_count_sweep_preserves_cliques(n_stripes):
    """Any stripe count yields the same clique set — boundary cliques
    are never lost to a short halo, never duplicated across owners."""
    sets = _field(800, seed=5)
    k = len(sets)
    base = run_consensus_giant(
        sets, BOX, n_stripes=1, use_mesh=False, spatial=False
    )
    want = _clique_keys(base["member_idx"][base["valid"]], k)
    got_res = run_consensus_giant(
        sets, BOX, n_stripes=n_stripes, use_mesh=False, spatial=False
    )
    got = _clique_keys(got_res["member_idx"][got_res["valid"]], k)
    assert got == want


def test_dir_striped_output_equals_batched(tmp_path):
    """`consensus --stripes S` writes byte-identical BOX files to the
    batched path on a real directory workload."""
    import os

    from repic_tpu.pipeline.consensus import run_consensus_dir
    from repic_tpu.utils.box_io import write_box

    src = tmp_path / "in"
    for p in range(3):
        d = src / f"picker{p}"
        d.mkdir(parents=True)
        for m in range(2):
            sets = _field(300, seed=10 * m + p)[0]
            write_box(
                str(d / f"mic{m}.box"), sets.xy, sets.conf, BOX
            )
    plain = str(tmp_path / "plain")
    striped = str(tmp_path / "striped")
    run_consensus_dir(str(src), plain, int(BOX), use_mesh=False)
    stats = run_consensus_dir(
        str(src), striped, int(BOX), use_mesh=False, stripes=4
    )
    assert stats["stripes"] == 4
    for m in range(2):
        with open(os.path.join(plain, f"mic{m}.box")) as f:
            want = f.read()
        with open(os.path.join(striped, f"mic{m}.box")) as f:
            got = f.read()
        assert got == want, f"mic{m}"

    # flag-surface validation: incompatible / invalid combinations
    # fail loudly, not via stripped asserts or deep numpy tracebacks
    with pytest.raises(ValueError, match="multi_out"):
        run_consensus_dir(
            str(src), str(tmp_path / "x1"), int(BOX),
            use_mesh=False, stripes=4, multi_out=True,
        )
    with pytest.raises(ValueError, match="stripes"):
        run_consensus_dir(
            str(src), str(tmp_path / "x2"), int(BOX),
            use_mesh=False, stripes=0,
        )
    with pytest.warns(UserWarning, match="striped"):
        run_consensus_dir(
            str(src), str(tmp_path / "x3"), int(BOX),
            use_mesh=False, stripes=4, use_pallas=True,
        )


def test_striped_mixed_k5_equals_unstriped():
    """k=5 mixed-box-size ensembles (the staged-join regime) through
    the striped path: any stripe count preserves the clique set."""
    sizes = np.asarray([180.0, 120.0, 180.0, 120.0, 180.0], np.float32)
    rng = np.random.default_rng(21)
    n = 400
    base = rng.uniform(200, 9000, size=(n, 2)).astype(np.float32)
    sets = []
    for p in range(5):
        xy = base + rng.normal(0, 8, base.shape).astype(np.float32)
        sets.append(
            BoxSet(
                xy=xy,
                conf=rng.uniform(0.05, 1.0, size=n).astype(np.float32),
                wh=np.full((n, 2), sizes[p], np.float32),
            )
        )
    base_res = run_consensus_giant(
        sets, sizes, n_stripes=1, use_mesh=False, spatial=False
    )
    striped = run_consensus_giant(
        sets, sizes, n_stripes=8, use_mesh=False, spatial=False
    )
    k = 5
    assert _clique_keys(
        striped["member_idx"][striped["valid"]], k
    ) == _clique_keys(base_res["member_idx"][base_res["valid"]], k)
    assert striped["num_cliques"] == base_res["num_cliques"] > 0


def test_stripes_auto_resolution(tmp_path):
    """'auto' stripes only when micrographs < devices AND fields are
    dense; otherwise it silently takes the batched path (including
    with the table flags, which need it)."""
    from repic_tpu.pipeline.consensus import run_consensus_dir
    from repic_tpu.utils.box_io import write_box

    src = tmp_path / "in"
    for p in range(2):
        d = src / f"picker{p}"
        d.mkdir(parents=True)
        s = _field(200, k=1, seed=p)[0]
        write_box(str(d / "m0.box"), s.xy, s.conf, BOX)
    # sparse single micrograph on the 8-device mesh: auto -> batched
    stats = run_consensus_dir(
        str(src), str(tmp_path / "o1"), int(BOX), stripes="auto"
    )
    assert "stripes" not in stats
    # auto + multi_out must not conflict (resolves to batched)
    stats = run_consensus_dir(
        str(src), str(tmp_path / "o2"), int(BOX),
        stripes="auto", multi_out=True,
    )
    assert "stripes" not in stats
    # dense single micrograph, fewer micrographs than devices: stripes
    src2 = tmp_path / "in2"
    for p in range(2):
        d = src2 / f"picker{p}"
        d.mkdir(parents=True)
        s = _field(5000, k=1, seed=p)[0]
        write_box(str(d / "m0.box"), s.xy, s.conf, BOX)
    stats = run_consensus_dir(
        str(src2), str(tmp_path / "o3"), int(BOX), stripes="auto"
    )
    assert stats.get("stripes", 0) >= 8


def test_empty_and_tiny_stripes():
    """More stripes than anchors: the extra stripes are empty and the
    result still matches."""
    sets = _field(12, seed=9)
    k = len(sets)
    res = run_consensus_giant(
        sets, BOX, n_stripes=16, use_mesh=False, spatial=False
    )
    base = run_consensus_giant(
        sets, BOX, n_stripes=1, use_mesh=False, spatial=False
    )
    assert _clique_keys(
        res["member_idx"][res["valid"]], k
    ) == _clique_keys(base["member_idx"][base["valid"]], k)
