"""Unit tests for the pairwise IoU kernel vs closed-form cases."""

import numpy as np
import jax.numpy as jnp

from repic_tpu.ops.iou import pair_iou, pairwise_iou_matrix


def ref_jaccard(x, y, a, b, box):
    """Closed-form oracle: IoU of equal-size axis-aligned boxes."""
    xo = max(min(x, a) + box - max(x, a), 0)
    yo = max(min(y, b) + box - max(y, b), 0)
    inter = xo * yo
    return inter / (2 * box * box - inter)


def test_identical_boxes():
    xy = jnp.array([[10.0, 20.0]])
    assert np.allclose(pair_iou(xy, xy, 100.0), 1.0)


def test_disjoint_boxes():
    a = jnp.array([[0.0, 0.0]])
    b = jnp.array([[500.0, 0.0]])
    assert np.allclose(pair_iou(a, b, 100.0), 0.0)


def test_half_shift():
    # shift by half the box in x: inter = b/2 * b, union = 2b^2 - inter
    a = jnp.array([[0.0, 0.0]])
    b = jnp.array([[50.0, 0.0]])
    expect = (50 * 100) / (2 * 100 * 100 - 50 * 100)
    assert np.allclose(pair_iou(a, b, 100.0), expect)


def test_touching_edges_zero():
    a = jnp.array([[0.0, 0.0]])
    b = jnp.array([[100.0, 0.0]])
    assert np.allclose(pair_iou(a, b, 100.0), 0.0)


def test_matches_oracle_random(rng):
    box = 180.0
    a = rng.uniform(0, 4000, size=(60, 2)).astype(np.float32)
    b = rng.uniform(0, 4000, size=(70, 2)).astype(np.float32)
    got = np.asarray(pair_iou(jnp.asarray(a), jnp.asarray(b), box))
    want = np.array(
        [[ref_jaccard(x, y, p, q, box) for (p, q) in b] for (x, y) in a]
    )
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_masked_entries_zero(rng):
    a = rng.uniform(0, 400, size=(8, 2)).astype(np.float32)
    mask_a = np.array([True] * 4 + [False] * 4)
    m = np.asarray(
        pairwise_iou_matrix(
            jnp.asarray(a), jnp.asarray(mask_a), jnp.asarray(a),
            jnp.asarray(mask_a), 180.0,
        )
    )
    assert np.all(m[4:] == 0) and np.all(m[:, 4:] == 0)
    np.testing.assert_allclose(np.diag(m[:4, :4]), 1.0, rtol=1e-5)
