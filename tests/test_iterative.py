"""Iterative ensemble pipeline tests: splits, semi-auto seeding,
adapter command templates, and a full in-process end-to-end run with
three builtin JAX pickers on planted synthetic particles."""

import glob
import json
import os

import numpy as np
import pytest

from repic_tpu.pipeline import iterative, pickers as pickers_mod
from test_train import PARTICLE, make_micrograph, write_pair


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """Synthetic micrograph dir + full manual labels."""
    root = tmp_path_factory.mktemp("iterdata")
    data_dir = root / "mrc"
    label_dir = root / "labels"
    data_dir.mkdir()
    label_dir.mkdir()
    rng = np.random.default_rng(21)
    for i in range(8):
        img, centers = make_micrograph(rng, size=800, n_particles=10)
        write_pair(
            (str(data_dir), str(label_dir)), f"mic{i}", img, centers
        )
    return str(data_dir), str(label_dir)


def test_build_splits_partitions(dataset, tmp_path):
    data_dir, _ = dataset
    dirs = iterative.build_splits(data_dir, str(tmp_path))
    all_links = []
    for split, d in dirs.items():
        links = sorted(os.listdir(d))
        all_links += links
    assert len(all_links) == 8
    assert len(set(all_links)) == 8  # a micrograph lands in one split
    assert len(os.listdir(dirs["train"])) == 2  # 20% of 8


def test_build_splits_train_size_percent(dataset, tmp_path):
    data_dir, _ = dataset
    dirs = iterative.build_splits(
        data_dir, str(tmp_path), train_size=50
    )
    assert len(os.listdir(dirs["train"])) == 1


def test_build_splits_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        iterative.build_splits(str(tmp_path), str(tmp_path / "o"))


def test_seed_round0_sampling(dataset, tmp_path):
    data_dir, label_dir = dataset
    splits = iterative.build_splits(data_dir, str(tmp_path))
    out = iterative.seed_round0_from_manual(
        label_dir,
        splits,
        str(tmp_path / "r0"),
        fraction=0.5,
        box_size=PARTICLE,
    )
    from repic_tpu.utils.box_io import read_box

    files = glob.glob(os.path.join(out["train"], "*.box"))
    assert files
    for f in files:
        assert read_box(f).n == 5  # 50% of 10


def test_external_adapter_commands(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cry = pickers_mod.CryoloPicker(
        name="cryolo",
        conda_env="cryolo",
        particle_size=180,
        model_path="gmodel.h5",
    )
    cmd = cry.predict_cmd("in", "out", "cfg.json")
    assert "-t" in cmd and cmd[cmd.index("-t") + 1] == "0.0"
    assert "--write_empty" in cmd

    topaz = pickers_mod.TopazPicker(
        name="topaz",
        conda_env="topaz",
        particle_size=180,
        radius=12,
        balance=0.0321,
    )
    fit = topaz.fit_cmd("train", "targets.txt", "model", expected=300)
    assert str(int(300 * 1.25)) in fit
    assert "--minibatch-balance" in fit

    # predict wires through _run, which needs the conda env: absent
    # here, so it must fail with a diagnosable PickerError (not an
    # AttributeError), before and after writing its config
    with pytest.raises(pickers_mod.PickerError):
        cry.predict(str(tmp_path / "in"), str(tmp_path / "out"))

    # the generic base adapter also raises PickerError, not
    # AttributeError
    base = pickers_mod.ExternalPicker(
        name="x", conda_env="nope", particle_size=180
    )
    with pytest.raises(pickers_mod.PickerError):
        base.predict("in", "out")
    with pytest.raises(pickers_mod.PickerError):
        base.fit()


def test_build_splits_defocus_file(dataset, tmp_path):
    """A defocus table routes through the stratified splitter."""
    data_dir, _ = dataset
    defocus = os.path.join(data_dir, "defocus.txt")
    rng = np.random.default_rng(3)
    with open(defocus, "wt") as f:
        for i in range(8):
            d = 10000 + 1000 * float(rng.uniform())
            f.write(f"mic{i}.mrc\t{d:.1f}\t{d + 50:.1f}\n")
    try:
        dirs = iterative.build_splits(data_dir, str(tmp_path))
        all_links = sorted(
            l for d in dirs.values() for l in os.listdir(d)
        )
        assert len(all_links) == 8 and len(set(all_links)) == 8
    finally:
        os.remove(defocus)


def test_build_splits_reset_on_rerun(dataset, tmp_path):
    """Re-running with a smaller train_size must not keep stale
    symlinks from the previous run."""
    data_dir, _ = dataset
    dirs = iterative.build_splits(data_dir, str(tmp_path))
    assert len(os.listdir(dirs["train"])) == 2
    dirs = iterative.build_splits(
        data_dir, str(tmp_path), train_size=50
    )
    assert len(os.listdir(dirs["train"])) == 1


def test_consensus_round_empty_split(tmp_path):
    """A split with zero loadable micrographs must not crash."""
    pdir = tmp_path / "pred"
    for picker in ("p1", "p2"):
        (pdir / picker).mkdir(parents=True)
    state = iterative.IterativeState(out_dir=str(tmp_path))
    out = iterative.consensus_round(
        {"train": str(pdir)}, str(tmp_path / "r"), 180, state
    )
    assert "train" in out


def test_topaz_tsv_box_roundtrip(tmp_path):
    """Extraction-table coordinates upscale back to the original grid
    and the BOX labels downscale back to the extraction grid."""
    mrc = tmp_path / "mrc"
    mrc.mkdir()
    (mrc / "a.mrc").write_bytes(b"")
    (mrc / "b.mrc").write_bytes(b"")
    tsv = tmp_path / "ex.txt"
    tsv.write_text(
        "image_name\tx_coord\ty_coord\tscore\n"
        "a\t100\t200\t0.9\n"
    )
    n = pickers_mod._topaz_tsv_to_box(
        str(tsv), str(tmp_path / "out"), 64, 4, str(mrc)
    )
    assert n == 1
    # empty placeholder for the micrograph topaz found nothing in
    assert (tmp_path / "out" / "b.box").exists()
    from repic_tpu.utils.box_io import read_box

    bs = read_box(str(tmp_path / "out" / "a.box"))
    assert tuple(bs.xy[0]) == (100 * 4 - 32, 200 * 4 - 32)

    back = pickers_mod._box_dir_to_topaz_tsv(
        str(tmp_path / "out"), str(tmp_path / "back.txt"), 64, 4
    )
    lines = (tmp_path / "back.txt").read_text().splitlines()
    assert lines[1] == "a\t100\t200"
    assert back == 1  # mean 0.5 over two micrographs, floored at 1


def test_build_pickers_shared_checkpoint_fallback():
    """cryolo_model is shared with builtin deep/topaz only when it is
    itself a repic-tpu checkpoint."""
    base = {"box_size": 180}
    ps = pickers_mod.build_pickers(
        dict(base, cryolo_model="init.rptpu")
    )
    assert [p.model_path for p in ps] == ["init.rptpu"] * 3
    # a SPHIRE-crYOLO .h5 must NOT leak into the builtin pickers
    ps = pickers_mod.build_pickers(dict(base, cryolo_model="g.h5"))
    assert [p.model_path for p in ps] == ["g.h5", None, None]
    # per-picker slots always win
    ps = pickers_mod.build_pickers(
        dict(base, cryolo_model="init.rptpu", deep_model="d.rptpu")
    )
    assert ps[1].model_path == "d.rptpu"


def test_build_pickers_compute_dtype_from_config(tmp_path):
    """iter_config --bf16 writes compute_dtype and the builtin
    ensemble picks it up; absent key defaults to float32."""
    from types import SimpleNamespace

    from repic_tpu.commands import iter_config

    base = {"box_size": 180}
    assert all(
        p.compute_dtype == "float32"
        for p in pickers_mod.build_pickers(base)
    )
    ps = pickers_mod.build_pickers(
        dict(base, compute_dtype="bfloat16")
    )
    assert all(p.compute_dtype == "bfloat16" for p in ps)

    out = tmp_path / "cfg.json"
    iter_config.main(
        SimpleNamespace(
            data_dir=str(tmp_path),
            box_size=180,
            exp_particles=100,
            cryolo_model="builtin",
            deep_dir="builtin",
            topaz_scale=4,
            topaz_rad=8,
            cryolo_env="builtin",
            deep_env="builtin",
            topaz_env="builtin",
            out_file_path=str(out),
            bf16=True,
        )
    )
    import json

    assert json.load(open(out))["compute_dtype"] == "bfloat16"


def test_builtin_picker_requires_model(tmp_path):
    p = pickers_mod.BuiltinPicker(name="b", particle_size=PARTICLE)
    with pytest.raises(pickers_mod.PickerError):
        p.predict(str(tmp_path), str(tmp_path / "o"))


def test_build_pickers_from_config():
    config = {
        "box_size": 180,
        "cryolo_env": "builtin",
        "deep_env": "builtin",
        "topaz_env": "topaz",
        "topaz_scale": 4,
        "topaz_rad": 9,
    }
    ps = pickers_mod.build_pickers(config)
    assert [p.name for p in ps] == ["cryolo", "deep", "topaz"]
    assert isinstance(ps[0], pickers_mod.BuiltinPicker)
    assert isinstance(ps[1], pickers_mod.BuiltinPicker)
    assert ps[0].seed != ps[1].seed  # ensemble diversity
    assert isinstance(ps[2], pickers_mod.TopazPicker)
    assert ps[2].radius == 9


@pytest.mark.slow
@pytest.mark.parametrize("compute_dtype", ["float32", "bfloat16"])
def test_iterative_end_to_end_builtin(dataset, tmp_path, compute_dtype):
    """Semi-auto round 0 from manual labels, one retraining round,
    three builtin pickers, consensus recovers planted particles —
    under both compute dtypes (bfloat16 = the MXU-native path the
    whole iterative pipeline runs with iter_config --bf16)."""
    data_dir, label_dir = dataset
    config = {
        "data_dir": data_dir,
        "box_size": PARTICLE,
        "exp_particles": 10,
        "cryolo_env": "builtin",
        "deep_env": "builtin",
        "topaz_env": "builtin",
        "compute_dtype": compute_dtype,
    }
    out_dir = str(tmp_path / "run")
    state = iterative.run_iterative(
        config,
        num_iter=1,
        train_size=100,
        out_dir=out_dir,
        semi_auto=True,
        manual_label_dir=label_dir,
        semi_auto_fraction=1.0,
        score_gt_dir=label_dir,
        picker_overrides={"max_epochs": 6, "batch_size": 16},
    )
    assert len(state.rounds) == 2
    # consensus BOX files exist for the final round's test split
    final = state.rounds[-1]["consensus"]
    test_boxes = glob.glob(os.path.join(final["test"], "*.box"))
    assert test_boxes
    # the scored F1 for the final round should be recorded in the log
    log = open(os.path.join(out_dir, "iter_pick.log")).read()
    assert "round 1" in log and "score round_1" in log
    assert os.path.exists(os.path.join(out_dir, "state.json"))
    # recovery check: each test-split micrograph's consensus should
    # find most planted particles
    from repic_tpu.utils.box_io import read_box

    f1s = []
    comp = os.path.join(final["test"], "particle_set_comp.tsv")
    assert os.path.exists(comp)
    with open(comp) as fh:
        next(fh)
        for line in fh:
            parts = line.split("\t")
            f1s.append(float(parts[3]))
    assert np.mean(f1s) > 0.5


def test_topaz_predict_cmd_enumerates_files(tmp_path):
    """subprocess has no shell: the extract command must list the
    downsampled micrographs explicitly, not pass a glob."""
    d = tmp_path / "down"
    d.mkdir()
    (d / "b.mrc").write_bytes(b"")
    (d / "a.mrc").write_bytes(b"")
    (d / "notes.txt").write_text("x")
    topaz = pickers_mod.TopazPicker(
        name="topaz", conda_env="topaz", particle_size=180
    )
    cmd = topaz.predict_cmd(str(d), "out.txt")
    assert str(d / "a.mrc") in cmd and str(d / "b.mrc") in cmd
    assert not any("*" in c for c in cmd)
    assert not any(c.endswith("notes.txt") for c in cmd)


def test_deep_predict_requires_model(tmp_path):
    deep = pickers_mod.DeepPickerExternal(
        name="deep", conda_env="deep", particle_size=180,
        deep_dir="/x",
    )
    with pytest.raises(pickers_mod.PickerError, match="no model"):
        deep.predict(str(tmp_path), str(tmp_path / "o"))
