"""Mid-run resume of the iterative pipeline (round-3 verdict item 6).

The reference leaves only a manual resume hint
(reference: repic/iterative_particle_picking/run.sh:228-229); here
``state.json`` is checkpointed after every completed round and
``run_iterative`` continues from the last one.  These tests drive the
orchestrator with lightweight fake pickers that record every
``fit``/``predict`` call, so "round 1 was NOT retrained" is asserted
directly on the call log.
"""

import glob
import json
import os

import numpy as np
import pytest

from repic_tpu.pipeline import iterative


class FakePicker:
    """Deterministic picker: same picks every call, records calls."""

    def __init__(self, name, particle_size, calls):
        self.name = name
        self.particle_size = particle_size
        self.model_path = None
        self.calls = calls  # shared list of (picker, op, detail)

    def predict(self, mrc_dir, out_box_dir):
        os.makedirs(out_box_dir, exist_ok=True)
        total = 0
        for mrc in sorted(glob.glob(os.path.join(mrc_dir, "*.mrc"))):
            stem = os.path.splitext(os.path.basename(mrc))[0]
            with open(
                os.path.join(out_box_dir, stem + ".box"), "wt"
            ) as f:
                # all fake pickers agree -> every pick survives
                # consensus
                for i in range(4):
                    f.write(
                        f"{100 + 90 * i}\t{120 + 90 * i}\t"
                        f"{self.particle_size}\t{self.particle_size}"
                        f"\t0.9\n"
                    )
                total += 4
        self.calls.append((self.name, "predict", mrc_dir))
        return total

    def fit(self, train_mrc, train_box, val_mrc, val_box, model_out):
        # record which model this round retrains FROM — the resume
        # assertion that round-2 training starts from round-1's model
        self.calls.append((self.name, "fit", self.model_path))
        with open(model_out, "wt") as f:
            f.write(f"model-{self.name}")
        self.model_path = model_out


@pytest.fixture
def env(tmp_path, monkeypatch):
    data_dir = tmp_path / "mrc"
    data_dir.mkdir()
    for i in range(8):
        (data_dir / f"mic{i}.mrc").write_bytes(b"\x00" * 32)
    calls = []
    monkeypatch.setattr(
        iterative.pickers_mod,
        "build_pickers",
        lambda config: [
            FakePicker(n, int(config["box_size"]), calls)
            for n in ("cryolo", "deep", "topaz")
        ],
    )
    config = {"data_dir": str(data_dir), "box_size": 48}
    return config, str(tmp_path / "run"), calls


def _fits_per_round(calls):
    return [c for c in calls if c[1] == "fit"]


def test_resume_continues_without_retraining(env):
    config, out_dir, calls = env

    # phase 1: a 1-round run completes (simulating a 3-round run
    # that died after round 1 — identical on-disk state)
    state = iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir
    )
    assert len(state.rounds) == 2  # round_0 + round_1
    fits_run1 = len(_fits_per_round(calls))
    assert fits_run1 == 3  # 3 pickers x 1 retraining round
    predicts_run1 = len([c for c in calls if c[1] == "predict"])

    # phase 2: re-invoke asking for 3 rounds; rounds 0-1 must be
    # skipped, rounds 2-3 run
    calls.clear()
    state2 = iterative.run_iterative(
        config, num_iter=3, train_size=100, out_dir=out_dir
    )
    assert len(state2.rounds) == 4
    fits = _fits_per_round(calls)
    assert len(fits) == 6  # 3 pickers x rounds {2, 3} only
    # the first retraining of the resumed run starts FROM the
    # round-1 checkpoints restored off disk, not from scratch
    round1_models = os.path.join(out_dir, "round_1", "models")
    assert all(
        f[2] == os.path.join(round1_models, f"{f[0]}.rptpu")
        for f in fits[:3]
    )
    # predict count scales with rounds actually run: run 1 covered
    # rounds {0, 1}, the resumed run covers rounds {2, 3} — same count
    assert len([c for c in calls if c[1] == "predict"]) == predicts_run1
    # resumed rounds recorded and checkpointed
    saved = json.load(open(os.path.join(out_dir, "state.json")))
    assert len(saved["rounds"]) == 4
    assert "resuming: rounds 0..1 already complete" in open(
        os.path.join(out_dir, "iter_pick.log")
    ).read()


def test_resume_noop_when_all_rounds_done(env):
    config, out_dir, calls = env
    iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir
    )
    calls.clear()
    state = iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir
    )
    assert len(state.rounds) == 2
    assert calls == []  # nothing re-run


def test_fingerprint_mismatch_restarts(env):
    config, out_dir, calls = env
    iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir
    )
    calls.clear()
    # a different seed changes the splits: resuming would mix
    # incompatible rounds, so the run must restart from round 0
    state = iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir, seed=7
    )
    assert len(state.rounds) == 2
    assert len(_fits_per_round(calls)) == 3  # round 1 retrained


def test_no_resume_flag_restarts(env):
    config, out_dir, calls = env
    iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir
    )
    calls.clear()
    iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir,
        resume=False,
    )
    assert len(_fits_per_round(calls)) == 3


def test_resume_ignores_rounds_with_missing_outputs(env):
    """A round whose consensus dirs were deleted is not trusted."""
    import shutil

    config, out_dir, calls = env
    iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir
    )
    # wipe round 1's consensus output; state.json still lists it
    shutil.rmtree(os.path.join(out_dir, "round_1", "consensus"))
    calls.clear()
    state = iterative.run_iterative(
        config, num_iter=1, train_size=100, out_dir=out_dir
    )
    assert len(state.rounds) == 2
    # round 0 intact -> skipped; round 1 re-run
    assert len(_fits_per_round(calls)) == 3
    assert any(
        c[1] == "predict" for c in calls
    )
