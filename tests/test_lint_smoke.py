"""Smoke tests for the ``repic-tpu lint`` entry points.

Same contract as tests/test_bench_smoke.py: CI and the runbook invoke
these as subprocesses, so argument-surface drift must fail a cheap
tier-1 test, not a CI job half an hour in.  The linter additionally
promises to import NO JAX (it must run in sub-second time in
environments with no accelerator), which the last test pins.
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=120):
    return subprocess.run(
        [sys.executable] + args,
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_lint_help_exits_zero():
    proc = _run(["-m", "repic_tpu.main", "lint", "--help"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "RT001" in proc.stdout  # rule IDs are documented in --help


def test_module_entry_help_exits_zero():
    proc = _run(["-m", "repic_tpu.analysis", "--help"])
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_list_rules_covers_the_pack():
    proc = _run(["-m", "repic_tpu.analysis", "--list-rules"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    for rule_id in (
        "RT001", "RT002", "RT003", "RT004", "RT005", "RT006",
        "RT201", "RT202", "RT203", "RT204",
    ):
        assert rule_id in proc.stdout, rule_id


def test_json_format_on_clean_tree(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = _run(
        ["-m", "repic_tpu.analysis", str(clean), "--format", "json"]
    )
    assert proc.returncode == 0, proc.stdout
    assert json.loads(proc.stdout) == []


def test_json_format_carries_machine_readable_fields(tmp_path):
    # CI annotations and the telemetry report consume this shape:
    # every finding must carry rule/severity/message/hint/path/line
    dirty = tmp_path / "repic_tpu"
    dirty.mkdir()
    bad = dirty / "dirty.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    proc = _run(
        ["-m", "repic_tpu.analysis", str(bad), "--format", "json"]
    )
    assert proc.returncode == 1, proc.stdout
    findings = json.loads(proc.stdout)
    assert findings, "expected an RT002 finding"
    f = findings[0]
    assert f["rule"] == "RT002"
    assert f["severity"] == "error"
    assert f["path"] == str(bad) and f["line"] == 5
    assert f["message"] and f["hint"]
    assert set(f) == {
        "rule", "severity", "message", "hint", "path", "line", "col",
    }


def test_sarif_format_on_clean_tree(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = _run(
        ["-m", "repic_tpu.analysis", str(clean), "--format", "sarif"]
    )
    assert proc.returncode == 0, proc.stdout
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"] == []


def test_sarif_format_carries_code_scanning_fields(tmp_path):
    # GitHub code scanning ingests this shape (docs/static_analysis.md
    # "SARIF"): pinned here so renderer drift fails a tier-1 test,
    # not an upload half an hour into CI
    dirty = tmp_path / "repic_tpu"
    dirty.mkdir()
    bad = dirty / "dirty.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    )
    proc = _run(
        ["-m", "repic_tpu.analysis", str(bad), "--format", "sarif"]
    )
    assert proc.returncode == 1, proc.stdout
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "repic-tpu-lint"
    assert driver["version"]
    rules = driver["rules"]
    by_id = {r["id"]: r for r in rules}
    # the rule table covers every pack that can contribute findings
    for rule_id in ("RT002", "RT101", "RT201", "RT301", "RT305"):
        r = by_id[rule_id]
        assert r["shortDescription"]["text"]
        assert r["help"]["text"]
        assert r["defaultConfiguration"]["level"] in (
            "error", "warning", "note",
        )
    results = run["results"]
    assert results, "expected an RT002 result"
    res = results[0]
    assert res["ruleId"] == "RT002"
    assert rules[res["ruleIndex"]]["id"] == "RT002"
    assert res["level"] == "error"
    assert res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("dirty.py")
    assert loc["region"]["startLine"] == 5
    assert loc["region"]["startColumn"] >= 1


def test_lint_help_documents_concurrency_and_sarif():
    proc = _run(["-m", "repic_tpu.main", "lint", "--help"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "--concurrency" in proc.stdout
    assert "sarif" in proc.stdout


def test_list_rules_covers_the_concurrency_pack():
    proc = _run(["-m", "repic_tpu.analysis", "--list-rules"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    for rule_id in ("RT301", "RT302", "RT303", "RT304", "RT305"):
        assert rule_id in proc.stdout, rule_id


def test_selecting_an_rt3xx_rule_enables_the_pass(tmp_path):
    # --select RT303 without --concurrency must still run the
    # whole-program pass (a select that silently no-ops reads green)
    bad = tmp_path / "mod.py"
    bad.write_text(
        "import threading\n"
        "import time\n"
        "LOCK = threading.Lock()\n"
        "\n"
        "\n"
        "def f():\n"
        "    with LOCK:\n"
        "        time.sleep(1.0)\n"
    )
    proc = _run(
        ["-m", "repic_tpu.analysis", str(bad), "--select", "RT303"]
    )
    assert proc.returncode == 1, proc.stdout
    assert "RT303" in proc.stdout


def test_check_help_exits_zero():
    proc = _run(["-m", "repic_tpu.main", "check", "--help"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "RT101" in proc.stdout  # rule IDs documented in --help


def test_lint_help_documents_deep_mode():
    proc = _run(["-m", "repic_tpu.main", "lint", "--help"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "--deep" in proc.stdout


def test_unknown_select_is_a_usage_error():
    proc = _run(["-m", "repic_tpu.analysis", "--select", "RT999"])
    assert proc.returncode != 0
    assert "RT999" in proc.stderr


def test_list_rules_covers_the_spmd_and_kernel_packs():
    proc = _run(["-m", "repic_tpu.analysis", "--list-rules"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    for rule_id in (
        "RT401", "RT402", "RT403", "RT404",
        "RT421", "RT422", "RT423", "RT424", "RT425",
    ):
        assert rule_id in proc.stdout, rule_id


def test_selecting_an_rt40x_rule_enables_the_spmd_pass(tmp_path):
    # --select RT401 without --spmd must still run the whole-program
    # pass (a select that silently no-ops reads green)
    bad = tmp_path / "mod.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "\n"
        "def f(x):\n"
        "    if jax.process_index() == 0:\n"
        "        x = jax.lax.psum(x, 'i')\n"
        "    return x\n"
    )
    proc = _run(
        ["-m", "repic_tpu.analysis", str(bad), "--select", "RT401"]
    )
    assert proc.returncode == 1, proc.stdout
    assert "RT401" in proc.stdout


def test_lint_help_documents_spmd_mode():
    proc = _run(["-m", "repic_tpu.main", "lint", "--help"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "--spmd" in proc.stdout


def test_spmd_sarif_report_carries_the_rt4xx_rule_table(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "\n"
        "def f(x):\n"
        "    if jax.process_index() == 0:\n"
        "        x = jax.lax.psum(x, 'i')\n"
        "    return x\n"
    )
    proc = _run(
        [
            "-m", "repic_tpu.analysis", str(bad), "--spmd",
            "--format", "sarif",
        ]
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    rules = {
        r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]
    }
    assert {"RT401", "RT402", "RT403", "RT404"} <= rules
    assert {"RT421", "RT422", "RT423", "RT424", "RT425"} <= rules
    assert any(
        r["ruleId"] == "RT401" for r in doc["runs"][0]["results"]
    )


def test_linter_imports_no_jax():
    # JAX startup costs seconds and needs an XLA client; the linter
    # must stay importable and runnable without it (CI lint step).
    code = (
        "import sys\n"
        "import repic_tpu.analysis\n"
        "from repic_tpu.analysis import run_paths\n"
        "run_paths([])\n"
        "assert 'jax' not in sys.modules, 'linter imported jax'\n"
    )
    proc = _run(["-c", code])
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_spmd_pass_imports_no_jax():
    # the RT40x pass (and the RT42x plan tables it shares a report
    # with) must obey the same stdlib-only discipline as lint
    code = (
        "import sys\n"
        "from repic_tpu.analysis.spmd import run_spmd\n"
        "from repic_tpu.analysis.kernels import KERNEL_RULES\n"
        "run_spmd([])\n"
        "assert 'jax' not in sys.modules, 'spmd pass imported jax'\n"
    )
    proc = _run(["-c", code])
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_list_rules_covers_the_cost_pack():
    proc = _run(["-m", "repic_tpu.analysis", "--list-rules"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    for rule_id in ("RT501", "RT502", "RT503", "RT511", "RT512"):
        assert rule_id in proc.stdout, rule_id


def test_selecting_an_rt5xx_rule_enables_the_cost_pass(tmp_path):
    # --select RT501 without --cost must still run the whole-program
    # pass (a select that silently no-ops reads green)
    bad = tmp_path / "mod.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def s1(x):\n"
        "    return x\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def s2(x):\n"
        "    return x\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def s3(x):\n"
        "    return x\n"
        "\n"
        "\n"
        "def pipeline(x):\n"
        "    a = s1(x)\n"
        "    b = s2(a)\n"
        "    c = s3(b)\n"
        "    return c\n"
    )
    proc = _run(
        ["-m", "repic_tpu.analysis", str(bad), "--select", "RT501"]
    )
    assert proc.returncode == 1, proc.stdout
    assert "RT501" in proc.stdout


def test_lint_help_documents_cost_mode():
    proc = _run(["-m", "repic_tpu.main", "lint", "--help"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "--cost" in proc.stdout


def test_check_select_redirects_cost_rules():
    # `check --select RT511` must not die with "unknown rule" (RT511
    # findings anchor on @checked/KernelContract lines, so reaching
    # for the contract checker is the natural mistake) — it points at
    # the lint --cost surface instead
    proc = _run(
        [
            "-m", "repic_tpu.main", "check",
            "--select", "RT511", "--list-entries",
        ]
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "lint --cost" in proc.stderr


def test_cost_pass_imports_no_jax():
    # the RT5xx pass sandboxes KernelContract plans with stdlib
    # BlockPlan stand-ins precisely so it never needs jax
    code = (
        "import sys\n"
        "from repic_tpu.analysis.cost import run_cost\n"
        "run_cost([])\n"
        "assert 'jax' not in sys.modules, 'cost pass imported jax'\n"
    )
    proc = _run(["-c", code])
    assert proc.returncode == 0, proc.stderr[-2000:]
