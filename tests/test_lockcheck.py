"""Unit tests for the REPIC_TPU_LOCKCHECK runtime sanitizer.

The sanitizer is the dynamic half of the RT3xx concurrency pass
(docs/static_analysis.md "LOCKCHECK"): it records real lock
acquisition order and unguarded-write witnesses during the tier-1
suite.  These tests pin its reporting contract — a witnessed
lock-order cycle and an unguarded write must each surface as a
structured violation — plus the scoping rules (only repic_tpu/test
frames get checked locks) and the install/uninstall reversibility the
conftest hook relies on.

Every test that deliberately records a violation runs inside
``lockcheck.scoped()`` so the recording cannot leak into the
process-wide state and fail the session-level gate when this file
itself runs under ``REPIC_TPU_LOCKCHECK=1``.
"""

import threading

from repic_tpu.analysis import lockcheck


def _locked_pair():
    a = lockcheck.checked_lock("site:A")
    b = lockcheck.checked_lock("site:B")
    return a, b


# -- lock protocol -----------------------------------------------------


def test_checked_lock_is_a_context_manager_lock():
    lock = lockcheck.checked_lock("site:cm")
    assert not lock.locked()
    with lock:
        assert lock.locked()
        assert lock.held_by_current_thread()
    assert not lock.locked()
    assert not lock.held_by_current_thread()


def test_checked_lock_nonblocking_acquire_failure_records_nothing():
    lock = lockcheck.checked_lock("site:nb")
    with lockcheck.scoped():
        lockcheck.reset()
        other_holds = threading.Event()
        release = threading.Event()

        def holder():
            with lock:
                other_holds.set()
                release.wait(5)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert other_holds.wait(5)
        assert lock.acquire(blocking=False) is False
        # a failed acquire must not appear on the held stack
        assert not lock.held_by_current_thread()
        release.set()
        t.join(5)
        assert lockcheck.violations() == []


def test_checked_rlock_reentry_is_not_a_violation():
    lock = lockcheck.checked_lock("site:re", kind="rlock")
    with lockcheck.scoped():
        lockcheck.reset()
        with lock:
            with lock:
                pass
        assert lockcheck.violations() == []
        # self-reentry adds no self-edge either
        assert lockcheck.edges().get("site:re", set()) == set()


# -- cycle reporting ---------------------------------------------------


def test_consistent_order_is_clean():
    a, b = _locked_pair()
    with lockcheck.scoped():
        lockcheck.reset()
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lockcheck.violations() == []
        assert "site:B" in lockcheck.edges()["site:A"]


def test_reversed_order_reports_a_cycle():
    a, b = _locked_pair()
    with lockcheck.scoped():
        lockcheck.reset()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        got = lockcheck.violations()
        assert len(got) == 1, got
        v = got[0]
        assert v["kind"] == "lock-order-cycle"
        # the cycle names both sites, and the detail is readable
        assert set(v["cycle"]) == {"site:A", "site:B"}
        assert "site:A" in v["detail"] and "site:B" in v["detail"]
        # the report the pytest hook prints carries the detail
        assert "lock-order-cycle" in lockcheck.report_text()


def test_three_lock_cycle_is_witnessed():
    a = lockcheck.checked_lock("site:A")
    b = lockcheck.checked_lock("site:B")
    c = lockcheck.checked_lock("site:C")
    with lockcheck.scoped():
        lockcheck.reset()
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        assert lockcheck.violations() == []  # no cycle yet
        with c:
            with a:
                pass
        got = lockcheck.violations()
        assert len(got) == 1, got
        assert got[0]["kind"] == "lock-order-cycle"
        assert set(got[0]["cycle"]) == {"site:A", "site:B", "site:C"}


def test_cycle_witnessed_across_threads():
    """The graph is process-wide: thread 1 takes A->B, thread 2 takes
    B->A — neither thread alone sees a cycle, the merged graph does
    (this is exactly the deadlock the static RT302 reports)."""
    a, b = _locked_pair()
    with lockcheck.scoped():
        lockcheck.reset()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1, daemon=True)
        th1.start()
        th1.join(5)
        th2 = threading.Thread(target=t2, daemon=True)
        th2.start()
        th2.join(5)
        got = lockcheck.violations()
        assert len(got) == 1, got
        assert got[0]["kind"] == "lock-order-cycle"


# -- unguarded-write witness (RT301 dynamic half) ---------------------


def test_note_write_without_lock_is_a_violation():
    lock = lockcheck.checked_lock("site:guard")
    with lockcheck.scoped():
        lockcheck.reset()
        assert lockcheck.note_write("Jobs._state", lock) is False
        got = lockcheck.violations()
        assert len(got) == 1, got
        v = got[0]
        assert v["kind"] == "unguarded-write"
        assert v["what"] == "Jobs._state"
        assert v["lock"] == "site:guard"
        assert "Jobs._state" in v["detail"]
        assert "unguarded-write" in lockcheck.report_text()


def test_note_write_with_lock_held_is_clean():
    lock = lockcheck.checked_lock("site:guard")
    with lockcheck.scoped():
        lockcheck.reset()
        with lock:
            assert lockcheck.note_write("Jobs._state", lock) is True
        assert lockcheck.violations() == []


def test_note_write_held_on_another_thread_is_a_violation():
    lock = lockcheck.checked_lock("site:guard")
    with lockcheck.scoped():
        lockcheck.reset()
        holder_has_it = threading.Event()
        release = threading.Event()

        def holder():
            with lock:
                holder_has_it.set()
                release.wait(5)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert holder_has_it.wait(5)
        # held, but by a DIFFERENT thread: this write is unguarded
        assert lockcheck.note_write("shared", lock) is False
        release.set()
        t.join(5)
        assert lockcheck.violations()[0]["kind"] == "unguarded-write"


def test_note_write_is_a_noop_for_raw_locks():
    # code paths call note_write unconditionally; with the sanitizer
    # off the lock is a plain threading primitive and must pass.
    # _thread.allocate_lock is the raw primitive even while the
    # factories are patched (this file runs under LOCKCHECK in CI)
    import _thread

    with lockcheck.scoped():
        lockcheck.reset()
        raw = _thread.allocate_lock()
        assert lockcheck.note_write("x", raw) is True
        assert lockcheck.violations() == []


# -- isolation + reporting surface ------------------------------------


def test_scoped_restores_prior_state():
    a, b = _locked_pair()
    with lockcheck.scoped():
        lockcheck.reset()
        with a:
            with b:
                pass
        before_edges = lockcheck.edges()
        before_violations = lockcheck.violations()
        with lockcheck.scoped():
            with b:
                with a:
                    pass
            assert lockcheck.violations()  # visible inside
        # ... but not outside
        assert lockcheck.violations() == before_violations
        assert lockcheck.edges() == before_edges


def test_reset_clears_graph_and_violations():
    a, b = _locked_pair()
    with lockcheck.scoped():
        with b:
            with a:
                pass
        with a:
            with b:
                pass
        assert lockcheck.violations()
        lockcheck.reset()
        assert lockcheck.violations() == []
        assert lockcheck.edges() == {}
        assert "no violations" in lockcheck.report_text()


# -- install scoping ---------------------------------------------------


def test_install_patches_factories_and_uninstall_restores():
    was = lockcheck.installed()
    try:
        assert lockcheck.install() is True
        assert lockcheck.installed()
        assert lockcheck.install() is True  # idempotent
        # this test module matches the repic/test scope, so a Lock
        # allocated HERE is checked ...
        lock = threading.Lock()
        assert isinstance(lock, lockcheck.CheckedLock)
        assert lock.kind == "lock"
        assert "test_lockcheck" in lock.site
        rlock = threading.RLock()
        assert isinstance(rlock, lockcheck.CheckedLock)
        assert rlock.kind == "rlock"
        # ... while a frame from a foreign module gets a raw lock
        # (stdlib/jax internals must see zero overhead)
        ns = {"__name__": "somelib.pool", "threading": threading}
        exec("lock = threading.Lock()", ns)
        assert not isinstance(ns["lock"], lockcheck.CheckedLock)
    finally:
        lockcheck.uninstall()
        if was:  # the suite runs under REPIC_TPU_LOCKCHECK=1
            lockcheck.install()
    if not was:
        assert not isinstance(
            threading.Lock(), lockcheck.CheckedLock
        )


def test_maybe_install_from_env_respects_the_env_var(monkeypatch):
    was = lockcheck.installed()
    try:
        lockcheck.uninstall()
        monkeypatch.delenv(lockcheck.ENV_VAR, raising=False)
        assert lockcheck.enabled() is False
        assert lockcheck.maybe_install_from_env() is False
        assert not lockcheck.installed()
        monkeypatch.setenv(lockcheck.ENV_VAR, "1")
        assert lockcheck.enabled() is True
        assert lockcheck.maybe_install_from_env() is True
        assert lockcheck.installed()
    finally:
        lockcheck.uninstall()
        if was:
            lockcheck.install()


def test_checked_locks_survive_uninstall():
    """The conftest hook may uninstall while daemon threads still hold
    checked locks — those proxies must keep delegating to their real
    primitives."""
    was = lockcheck.installed()
    try:
        lockcheck.install()
        lock = threading.Lock()
        assert isinstance(lock, lockcheck.CheckedLock)
    finally:
        lockcheck.uninstall()
        if was:
            lockcheck.install()
    with lockcheck.scoped():
        with lock:
            assert lock.locked()
        assert not lock.locked()
