"""Fused megakernel chunk program (``solver="lp_device_fused"``).

The ISSUE 19 acceptance surface: golden fused-vs-staged equality
across the ragged shape ladder (including an all-masked micrograph
and a zero-clique field), dir-level BOX byte-identity on the
examples/10017 reference set, the ``megakernel_fallback`` fault
site's journaled ladder demotion, KERNELCHECK differential probes of
both fused contracts, and the one-deep chunk prefetch that overlaps
BOX emission with device compute.

The equality contract everywhere below: fused and staged programs
agree on the valid mask, on ``picked`` over the FULL buffer, and on
every field restricted to valid rows.  Rows past the compaction
frontier carry whatever each program's scatter left there —
different garbage, read by nothing — so full-buffer equality of
``member_idx``/``rep_slot``/``rep_xy`` is NOT part of the contract
and legitimately fails.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

from repic_tpu.parallel.batching import pad_batch
from repic_tpu.pipeline import consensus as C
from repic_tpu.pipeline.consensus import run_consensus_batch, run_consensus_dir
from repic_tpu.runtime import faults
from repic_tpu.runtime.journal import read_journal
from repic_tpu.utils.box_io import BoxSet
from tests.conftest import REFERENCE_EXAMPLES, needs_reference

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from bench_stress import synthesize  # noqa: E402

FORCE_ENV = "REPIC_TPU_MEGAKERNEL_FORCE"
_VALID_ROW_FIELDS = ("member_idx", "rep_slot", "w", "confidence", "rep_xy")


def _assert_fused_matches_staged(res_staged, res_fused):
    valid = np.asarray(res_staged.valid)
    np.testing.assert_array_equal(
        valid, np.asarray(res_fused.valid), err_msg="valid"
    )
    np.testing.assert_array_equal(
        np.asarray(res_staged.picked),
        np.asarray(res_fused.picked),
        err_msg="picked",
    )
    for f in _VALID_ROW_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(res_staged, f))[valid],
            np.asarray(getattr(res_fused, f))[valid],
            err_msg=f,
        )


def _run_both(batch, box_size, monkeypatch, **kw):
    """Staged then fused (kernel forced into interpret mode) on the
    same batch; capacity escalation from the first run is reused by
    the second, so both solve at identical static shapes."""
    monkeypatch.delenv(FORCE_ENV, raising=False)
    res_staged = run_consensus_batch(
        batch, box_size, use_mesh=False, solver="lp_device", **kw
    )
    monkeypatch.setenv(FORCE_ENV, "1")
    res_fused = run_consensus_batch(
        batch, box_size, use_mesh=False, solver="lp_device_fused", **kw
    )
    monkeypatch.delenv(FORCE_ENV, raising=False)
    return res_staged, res_fused


def _batch(m=2, k=3, n=64, seed=0):
    from repic_tpu.parallel.batching import PaddedBatch

    xy, conf, mask = synthesize(m, k, n, seed=seed)
    return PaddedBatch(
        xy=xy, conf=conf, mask=mask,
        names=tuple(f"m{i}" for i in range(m)),
        counts=np.full((m, k), n, np.int32),
    )


# -- golden fused-vs-staged over the shape ladder ---------------------


# k=3 is exercised by the ragged-counts test below and the 10017
# byte-identity run; parametrizing it here too would only add two
# more XLA compiles to the tier-1 wall clock
@pytest.mark.parametrize("k", [2, 4])
def test_fused_matches_staged(monkeypatch, k):
    batch = _batch(m=2, k=k, n=48, seed=k)
    res_s, res_f = _run_both(batch, 180.0, monkeypatch)
    assert int(np.sum(np.asarray(res_s.num_cliques))) > 0
    _assert_fused_matches_staged(res_s, res_f)


def test_fused_matches_staged_ragged_counts(monkeypatch):
    """Per-picker ragged particle counts (the pad_batch path)."""
    rng = np.random.default_rng(7)
    base = rng.uniform(100, 900, size=(40, 2)).astype(np.float32)

    def _set(n):
        xy = base[:n] + rng.normal(0, 8, size=(n, 2)).astype(np.float32)
        return BoxSet(
            xy=xy,
            conf=rng.uniform(0.1, 1.0, n).astype(np.float32),
            wh=np.full((n, 2), 64.0, np.float32),
        )

    loaded = [
        ("ragged0", [_set(40), _set(25), _set(33)]),
        ("ragged1", [_set(12), _set(40), _set(7)]),
    ]
    batch = pad_batch(loaded)
    res_s, res_f = _run_both(batch, 64.0, monkeypatch)
    assert int(np.sum(np.asarray(res_s.num_cliques))) > 0
    _assert_fused_matches_staged(res_s, res_f)


def test_fused_matches_staged_empty_and_zero_clique(monkeypatch):
    """One batch carrying the two degenerate shards — an all-masked
    micrograph (empty shard) and one whose pickers never overlap
    cross-picker (zero cliques) — next to a dense sibling: both
    programs return all-invalid buffers for the degenerate rows
    without perturbing the dense one.  (One batch = one compile pair
    for all three cases; the properties are per-micrograph.)"""
    batch = _batch(m=3, k=3, n=32, seed=1)
    mask = batch.mask.copy()
    mask[1] = False
    counts = batch.counts.copy()
    counts[1] = 0
    # shove micrograph 2's pickers to mutually far-apart regions
    xy = batch.xy.copy()
    xy[2] += np.arange(3, dtype=np.float32).reshape(3, 1, 1) * 50_000.0
    batch = batch._replace(mask=mask, counts=counts, xy=xy)
    res_s, res_f = _run_both(batch, 180.0, monkeypatch)
    assert int(np.asarray(res_s.num_cliques)[0]) > 0   # dense sibling
    assert int(np.asarray(res_s.num_cliques)[1]) == 0  # empty shard
    assert int(np.asarray(res_s.num_cliques)[2]) == 0  # zero-clique
    assert not np.asarray(res_f.valid[1]).any()
    assert not np.asarray(res_f.valid[2]).any()
    _assert_fused_matches_staged(res_s, res_f)


# -- envelope + dispatch gating ---------------------------------------


def test_fused_envelope(monkeypatch):
    from repic_tpu.ops import megakernel as mk

    assert mk.fused_eligible(3, 1024, 16)
    assert mk.fused_eligible(2, 8192, 64)
    assert not mk.fused_eligible(1, 1024, 16)      # no join to fuse
    assert not mk.fused_eligible(7, 1024, 4)       # K past the envelope
    assert not mk.fused_eligible(3, 8193, 16)      # N past the envelope
    assert not mk.fused_eligible(4, 1024, 64)      # d^(k-1) product blowup
    assert not mk.fused_eligible(
        3, 1024, 16, spatial_grid=(8, 8)
    )                                              # bucketed path owns grids

    monkeypatch.delenv(FORCE_ENV, raising=False)
    import jax

    assert mk.kernel_requested() == (jax.default_backend() == "tpu")
    for val in ("1", "true", "yes"):
        monkeypatch.setenv(FORCE_ENV, val)
        assert mk.kernel_requested()
    monkeypatch.setenv(FORCE_ENV, "0")
    assert mk.kernel_requested() == (jax.default_backend() == "tpu")


# -- KERNELCHECK: differential probes of the fused contracts ----------


@pytest.mark.slow
def test_kernelcheck_fused_contracts_zero_violations():
    """Both fused entries carry a KernelContract whose full shape
    ladder probes clean (interpret kernel vs pure-jnp reference).

    Marked slow (~15s of probe ladders): tier-1 already exercises the
    same contracts through ``repic-tpu check`` in CI's kernelcheck
    job, which runs this file without the marker filter."""
    import repic_tpu.ops.megakernel  # noqa: F401 — registers contracts
    from repic_tpu.analysis import contracts
    from repic_tpu.analysis.kernels import differential_probe

    entries = {
        name: e
        for name, e in contracts.registry().items()
        if "megakernel" in name
    }
    assert len(entries) >= 2, sorted(entries)
    for name, entry in sorted(entries.items()):
        kc = entry.contract.kernel
        assert kc is not None, name
        for dims in kc.ladder:
            msgs = differential_probe(entry, kc, dims=dims)
            assert not msgs, (name, dims, msgs)


# -- dir-level BOX byte-identity on the reference set -----------------


@needs_reference
def test_mini10017_fused_box_byte_identity(tmp_path, monkeypatch):
    """The fused rung writes byte-identical BOX files to the staged
    rung over the real examples/10017 picker set."""
    monkeypatch.delenv(FORCE_ENV, raising=False)
    out_s = str(tmp_path / "staged")
    run_consensus_dir(
        REFERENCE_EXAMPLES, out_s, 180, use_mesh=False,
        solver="lp_device",
    )
    monkeypatch.setenv(FORCE_ENV, "1")
    out_f = str(tmp_path / "fused")
    run_consensus_dir(
        REFERENCE_EXAMPLES, out_f, 180, use_mesh=False,
        solver="lp_device_fused",
    )
    boxes = sorted(
        f for f in os.listdir(out_s) if f.endswith(".box")
    )
    assert boxes
    for f in boxes:
        with open(os.path.join(out_s, f), "rb") as fh:
            staged = fh.read()
        with open(os.path.join(out_f, f), "rb") as fh:
            fused = fh.read()
        assert staged == fused, f


# -- megakernel_fallback: journaled ladder demotion -------------------


def _make_dir(tmp_path, m=4, k=3, n=24, seed=0):
    rng = np.random.default_rng(seed)
    d = tmp_path / "picks"
    for p in range(k):
        (d / f"picker{p}").mkdir(parents=True)
    for i in range(m):
        base = rng.uniform(50, 950, size=(n, 2))
        for p in range(k):
            jit = rng.normal(0, 10, size=base.shape)
            conf = rng.uniform(0.1, 1.0, size=n)
            with open(d / f"picker{p}" / f"mic{i}.box", "wt") as f:
                for (x, y), c in zip(base + jit, conf):
                    f.write(f"{x:.2f}\t{y:.2f}\t64\t64\t{c:.4f}\n")
    return str(d)


@pytest.mark.faults
def test_megakernel_fallback_demotes_and_journals(tmp_path, monkeypatch):
    """A planted ``megakernel_fallback`` firing re-solves exactly the
    named micrograph on the host ladder from the staged rung, marks
    it degraded, journals the demotion with the fused rung named, and
    leaves every sibling on the fused rung — with outputs written
    for all."""
    monkeypatch.setenv(FORCE_ENV, "1")
    data = _make_dir(tmp_path)
    out = str(tmp_path / "out")
    with faults.fault_plan("megakernel_fallback:mic1:1"):
        stats = run_consensus_dir(
            data, out, 64, use_mesh=False, solver="lp_device_fused"
        )
        assert ("megakernel_fallback", "mic1") in faults.fired_log()
    assert sorted(stats["particle_counts"]) == [
        f"mic{i}" for i in range(4)
    ]
    for i in range(4):
        assert os.path.exists(os.path.join(out, f"mic{i}.box"))
    latest = {e["name"]: e for e in read_journal(out) if "name" in e}
    assert latest["mic1"]["solver"] in ("lp_device", "lp", "greedy")
    assert latest["mic1"]["status"] == "degraded"
    for i in (0, 2, 3):
        assert latest[f"mic{i}"]["status"] == "ok"
    events = [
        e for e in read_journal(out)
        if e.get("event") == "solver_degraded"
    ]
    assert len(events) == 1
    assert events[0]["micrograph"] == "mic1"
    assert events[0]["rung"] == "lp_device_fused"
    assert events[0]["reason"] == "megakernel_fallback"


# (the clean-run fused directory surface — every micrograph ok, no
# demotion — is covered by the 10017 byte-identity run above and the
# ok-siblings assertions of the fallback test)


# -- chunk prefetch: overlap device compute with BOX emission ---------


def _prefetch_threads():
    return [
        t for t in threading.enumerate()
        if t.name == "repic-chunk-prefetch" and t.is_alive()
    ]


def test_prefetch_preserves_sequence():
    def gen():
        yield from range(100)

    assert list(C._prefetch_chunks(gen())) == list(range(100))
    assert not _prefetch_threads()


def test_prefetch_propagates_generator_error():
    def gen():
        yield 0
        yield 1
        raise ValueError("boom at item 2")

    it = C._prefetch_chunks(gen())
    assert next(it) == 0
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom at item 2"):
        next(it)
    assert not _prefetch_threads()


def test_prefetch_early_close_stops_worker():
    """Closing the consumer mid-stream must join the worker and close
    the inner generator (no orphan thread keeps pulling chunks)."""
    closed = threading.Event()

    def gen():
        try:
            i = 0
            while True:
                yield i
                i += 1
        finally:
            closed.set()

    it = C._prefetch_chunks(gen())
    assert next(it) == 0
    it.close()
    assert closed.wait(timeout=10.0)
    deadline = time.time() + 10.0
    while _prefetch_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert not _prefetch_threads()


def test_prefetch_counts_overlapped_chunks():
    """A slow consumer behind a fast producer registers overlap on
    the ``repic_consensus_prefetched_chunks_total`` counter."""
    before = C._PREFETCHED_CHUNKS.value()

    def gen():
        yield from range(5)

    for _ in C._prefetch_chunks(gen()):
        time.sleep(0.02)
    assert C._PREFETCHED_CHUNKS.value() > before


def test_prefetch_env_escape_hatch(monkeypatch):
    monkeypatch.delenv(C.NO_PREFETCH_ENV, raising=False)
    assert not C._prefetch_disabled()
    for val in ("1", "true", "YES"):
        monkeypatch.setenv(C.NO_PREFETCH_ENV, val)
        assert C._prefetch_disabled()
    monkeypatch.setenv(C.NO_PREFETCH_ENV, "0")
    assert not C._prefetch_disabled()


def test_prefetch_dir_run_byte_identity(tmp_path, monkeypatch):
    """A multi-chunk directory run emits byte-identical BOX files
    with the prefetch worker on and off (the overlap is pure
    scheduling, never reordering or dropping chunks)."""
    data = _make_dir(tmp_path, m=6, seed=5)
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "2")  # force 3 chunks

    monkeypatch.setenv(C.NO_PREFETCH_ENV, "1")
    out_serial = str(tmp_path / "serial")
    run_consensus_dir(data, out_serial, 64, use_mesh=False)

    monkeypatch.delenv(C.NO_PREFETCH_ENV, raising=False)
    out_prefetch = str(tmp_path / "prefetch")
    run_consensus_dir(data, out_prefetch, 64, use_mesh=False)
    assert not _prefetch_threads()

    boxes = sorted(
        f for f in os.listdir(out_serial) if f.endswith(".box")
    )
    assert len(boxes) == 6
    for f in boxes:
        with open(os.path.join(out_serial, f), "rb") as fh:
            serial = fh.read()
        with open(os.path.join(out_prefetch, f), "rb") as fh:
            prefetched = fh.read()
        assert serial == prefetched, f
    # journal written from both worker and consumer threads stays
    # one-valid-JSON-object-per-line
    latest = {
        e["name"]: e
        for e in read_journal(out_prefetch)
        if "name" in e
    }
    assert sorted(latest) == [f"mic{i}" for i in range(6)]
