"""End-to-end k=5 mixed-box-size consensus (BASELINE configs[4] shape).

VERDICT round 1 item 4: the per-row-size writer branch and the
mixed-size IoU were only kernel-tested.  Here a synthetic 5-picker
ensemble with two box sizes runs through ``run_consensus_batch`` on
BOTH the dense and the spatial (bucketed) paths and through
``write_consensus_boxes``, validated against an independent numpy
oracle (brute-force 5-way enumeration + exact set-packing).
"""

import itertools

import numpy as np
import pytest

from repic_tpu.ops.solver import solve_exact_py
from repic_tpu.parallel.batching import pad_batch
from repic_tpu.pipeline.consensus import (
    run_consensus_batch,
    write_consensus_boxes,
)
from repic_tpu.utils.box_io import BoxSet

K = 5
SIZES = np.asarray([180.0, 120.0, 180.0, 120.0, 180.0], np.float32)
THRESH = 0.3


def _oracle_iou(a, b, sa, sb):
    """Mixed-size corner-box IoU: inter / (sa^2 + sb^2 - inter)."""
    ox = np.maximum(
        0.0, np.minimum(a[:, None, 0] + sa, b[None, :, 0] + sb)
        - np.maximum(a[:, None, 0], b[None, :, 0])
    )
    oy = np.maximum(
        0.0, np.minimum(a[:, None, 1] + sa, b[None, :, 1] + sb)
        - np.maximum(a[:, None, 1], b[None, :, 1])
    )
    inter = ox * oy
    return inter / (sa * sa + sb * sb - inter)


def _oracle_cliques(points, confs):
    """Brute-force enumeration of valid 5-cliques with weights.

    Returns dict {member_tuple: (weight, confidence)} reproducing the
    reference statistics (median member conf x median edge IoU).
    """
    n = [len(p) for p in points]
    ious = {}
    for p, q in itertools.combinations(range(K), 2):
        ious[(p, q)] = _oracle_iou(
            points[p], points[q], SIZES[p], SIZES[q]
        )
    out = {}
    for tup in itertools.product(*[range(m) for m in n]):
        edges = [
            ious[(p, q)][tup[p], tup[q]]
            for p, q in itertools.combinations(range(K), 2)
        ]
        if min(edges) > THRESH:
            conf = float(np.median([confs[p][tup[p]] for p in range(K)]))
            w = conf * float(np.median(edges))
            out[tup] = (w, conf)
    return out


@pytest.fixture(scope="module")
def workload():
    """2 micrographs: well-separated clusters (one particle per picker)
    plus decoy clusters where two pickers offer 2 candidates each, so
    the solver faces real conflicts."""
    rng = np.random.default_rng(42)
    micros = []
    for _ in range(2):
        pts = [[] for _ in range(K)]
        cfs = [[] for _ in range(K)]
        centers = rng.uniform(200, 3600, size=(8, 2))
        # enforce separation so clusters never interact
        centers = centers[
            np.lexsort((centers[:, 1], centers[:, 0]))
        ]
        centers[:, 0] = np.linspace(200, 3400, 8)
        for c in centers:
            for p in range(K):
                # big-box pickers are sloppy, small-box pickers tight:
                # weighted degree then favors small-box reps in some
                # cliques, exercising both sizes in the writer output
                jit = 30.0 if SIZES[p] == 180.0 else 4.0
                pts[p].append(c + rng.normal(0, jit, 2))
                cfs[p].append(rng.uniform(0.2, 1.0))
        # decoys: pickers 1 and 3 offer an extra shifted candidate
        for c in centers[:2]:
            for p in (1, 3):
                pts[p].append(c + rng.normal(0, 12, 2) + 30.0)
                cfs[p].append(rng.uniform(0.2, 1.0))
        points = [np.asarray(p, np.float32) for p in pts]
        confs = [np.asarray(c, np.float32) for c in cfs]
        micros.append((points, confs))
    return micros


@pytest.fixture(scope="module")
def batch(workload):
    loaded = []
    for i, (points, confs) in enumerate(workload):
        sets = [
            BoxSet(
                xy=points[p],
                conf=confs[p],
                wh=np.full((len(points[p]), 2), SIZES[p], np.float32),
            )
            for p in range(K)
        ]
        loaded.append((f"m{i}", sets))
    return pad_batch(loaded)


@pytest.fixture(scope="module")
def results(batch):
    dense = run_consensus_batch(
        batch, SIZES, use_mesh=False, spatial=False, max_neighbors=4
    )
    spatial = run_consensus_batch(
        batch, SIZES, use_mesh=False, spatial=True, max_neighbors=4
    )
    return dense, spatial


def _framework_cliques(res, i, batch):
    valid = np.asarray(res.valid[i])
    mem = np.asarray(res.member_idx[i])[valid]
    w = np.asarray(res.w[i])[valid]
    conf = np.asarray(res.confidence[i])[valid]
    picked = np.asarray(res.picked[i])[valid]
    return mem, w, conf, picked


def test_enumeration_matches_oracle(workload, batch, results):
    dense, spatial = results
    for res in (dense, spatial):
        for i, (points, confs) in enumerate(workload):
            oracle = _oracle_cliques(points, confs)
            mem, w, conf, _ = _framework_cliques(res, i, batch)
            mine = {
                tuple(int(v) for v in row): (float(wv), float(cv))
                for row, wv, cv in zip(mem, w, conf)
            }
            assert set(mine) == set(oracle)
            for key, (wv, cv) in oracle.items():
                np.testing.assert_allclose(mine[key][0], wv, rtol=1e-4)
                np.testing.assert_allclose(mine[key][1], cv, rtol=1e-5)


def test_solver_within_gate_of_oracle_exact(workload, batch, results):
    dense, spatial = results
    for res in (dense, spatial):
        for i, (points, confs) in enumerate(workload):
            oracle = _oracle_cliques(points, confs)
            keys = sorted(oracle)
            n_max = max(len(p) for p in points)
            vid = np.asarray(
                [
                    [p * n_max + key[p] for p in range(K)]
                    for key in keys
                ],
                np.int64,
            )
            wo = np.asarray([oracle[k][0] for k in keys], np.float64)
            exact = solve_exact_py(vid, wo)
            exact_val = wo[exact].sum()

            mem, w, _, picked = _framework_cliques(res, i, batch)
            got_val = w[picked].sum()
            assert got_val >= 0.98 * exact_val
            # feasibility: no particle reused across picked cliques
            used = [
                (p, int(row[p])) for row in mem[picked] for p in range(K)
            ]
            assert len(used) == len(set(used))


def test_mixed_size_writer_rows(tmp_path, batch, results):
    dense, _ = results
    counts = write_consensus_boxes(
        batch, dense, str(tmp_path), SIZES
    )
    assert counts and all(v > 0 for v in counts.values())
    # with_num_cliques rides the SAME packed single-transfer array
    # (head row, channel 0) — it must round-trip exactly, and the
    # written files must be byte-identical to the default path
    before = {
        name: (tmp_path / f"{name}.box").read_bytes() for name in counts
    }
    counts2, nc = write_consensus_boxes(
        batch, dense, str(tmp_path), SIZES, with_num_cliques=True
    )
    assert counts2 == counts
    assert nc.shape == (batch.xy.shape[0],)
    np.testing.assert_array_equal(
        nc, np.asarray(dense.num_cliques).astype(np.int64)
    )
    for name in counts:
        assert (tmp_path / f"{name}.box").read_bytes() == before[name]
    for name in counts:
        rows = [
            line.split("\t")
            for line in (tmp_path / f"{name}.box").read_text().splitlines()
        ]
        # every row carries its representative picker's box size
        assert {r[2] for r in rows} <= {"180", "120"}
        assert all(r[2] == r[3] for r in rows)
        # both sizes actually appear (5 pickers, 2 size classes)
        assert len({r[2] for r in rows}) == 2


def test_packed_probe_bitcast_exact():
    """Probes ride the packed transfer as int32 BITS in f32 lanes:
    values beyond f32's 2^24 exact-integer range (observed
    requirements can exceed any capacity) must round-trip exactly."""
    import jax.numpy as jnp

    from repic_tpu.pipeline.consensus import (
        _pack_box_outputs,
        _packed_probes,
        _unpack_box_outputs,
    )

    m, n = 2, 3
    big = 16_777_217  # 2^24 + 1: rounds if stored as a f32 value
    packed = np.asarray(
        _pack_box_outputs(
            jnp.ones((m, n), bool),
            jnp.zeros((m, n, 2), jnp.float32),
            jnp.zeros((m, n), jnp.float32),
            jnp.zeros((m, n), jnp.int32),
            jnp.asarray([big, 7], jnp.int32),       # num_cliques
            jnp.asarray([big + 2, 1], jnp.int32),   # max_adjacency
            jnp.asarray([2, 2], jnp.int32),         # max_cell_count
            jnp.asarray([2**30, 0], jnp.int32),     # max_partial
        )
    )
    probes = _packed_probes(packed)
    assert probes[0, 0] == big + 2
    assert probes[0, 1] == big
    assert probes[0, 3] == 2**30
    *_, nc = _unpack_box_outputs(packed)
    assert nc[0] == big and nc[1] == 7


def test_writer_uses_rep_slot_sizes_directly(tmp_path):
    """Deterministic cover of the per-row-size branch: crafted result
    with representatives from both size classes."""
    import jax.numpy as jnp

    from repic_tpu.parallel.batching import PaddedBatch
    from repic_tpu.pipeline.consensus import ConsensusResult

    c = 4
    res = ConsensusResult(
        rep_xy=jnp.asarray(
            [[[10.0, 20.0], [30.0, 40.0], [50.0, 60.0], [0.0, 0.0]]]
        ),
        confidence=jnp.asarray([[0.9, 0.8, 0.7, 0.0]]),
        w=jnp.asarray([[0.9, 0.8, 0.7, 0.0]]),
        member_idx=jnp.zeros((1, c, K), jnp.int32),
        rep_slot=jnp.asarray([[0, 1, 4, 0]], jnp.int32),
        picked=jnp.asarray([[True, True, True, False]]),
        valid=jnp.asarray([[True, True, True, False]]),
        num_cliques=jnp.asarray([3], jnp.int32),
        max_adjacency=jnp.asarray([1], jnp.int32),
        max_cell_count=jnp.asarray([0], jnp.int32),
    )
    batch = PaddedBatch(
        xy=np.zeros((1, K, 8, 2), np.float32),
        conf=np.zeros((1, K, 8), np.float32),
        mask=np.zeros((1, K, 8), bool),
        names=("m0",),
        counts=np.zeros((1, K), np.int32),
    )
    write_consensus_boxes(batch, res, str(tmp_path), SIZES)
    rows = [
        line.split("\t")
        for line in (tmp_path / "m0.box").read_text().splitlines()
    ]
    # slots 0 and 4 are size 180, slot 1 is 120
    assert [r[2] for r in rows] == ["180", "120", "180"]
    assert [r[3] for r in rows] == ["180", "120", "180"]


def test_dense_and_spatial_pick_identically(batch, results):
    dense, spatial = results
    for i in range(2):
        dk = {
            tuple(m)
            for m, p in zip(
                np.asarray(dense.member_idx[i]),
                np.asarray(dense.picked[i]),
            )
            if p
        }
        sk = {
            tuple(m)
            for m, p in zip(
                np.asarray(spatial.member_idx[i]),
                np.asarray(spatial.picked[i]),
            )
            if p
        }
        assert dk == sk
