"""Tests for MRC I/O (utils/mrc.py) and dataset splitting
(utils/subsets.py)."""

import os

import numpy as np
import pytest

from repic_tpu.utils import mrc as mrc_io
from repic_tpu.utils import subsets


# ------------------------- MRC I/O -------------------------


def test_mrc_roundtrip_2d(tmp_path):
    img = np.random.default_rng(0).normal(size=(48, 64)).astype(np.float32)
    path = str(tmp_path / "a.mrc")
    mrc_io.write_mrc(path, img)
    got = mrc_io.read_mrc(path)
    assert got.shape == (48, 64)
    np.testing.assert_array_equal(got, img)


def test_mrc_roundtrip_stack(tmp_path):
    vol = np.arange(2 * 4 * 6, dtype=np.float32).reshape(2, 4, 6)
    path = str(tmp_path / "v.mrc")
    mrc_io.write_mrc(path, vol)
    got = mrc_io.read_mrc(path)
    assert got.shape == (2, 4, 6)
    np.testing.assert_array_equal(got, vol)


def test_mrc_int16_mode(tmp_path):
    # hand-build a mode-1 file
    img = np.arange(12, dtype="<i2").reshape(3, 4)
    header = np.zeros(256, dtype="<i4")
    header[0:4] = (4, 3, 1, 1)
    header[53] = 0x00004444
    path = str(tmp_path / "i16.mrc")
    with open(path, "wb") as f:
        f.write(header.tobytes())
        f.write(img.tobytes())
    got = mrc_io.read_mrc(path)
    np.testing.assert_array_equal(got, img)


def test_mrc_extended_header_skipped(tmp_path):
    img = np.ones((2, 2), dtype="<f4")
    header = np.zeros(256, dtype="<i4")
    header[0:4] = (2, 2, 1, 2)
    header[23] = 128  # nsymbt
    header[53] = 0x00004444
    path = str(tmp_path / "ext.mrc")
    with open(path, "wb") as f:
        f.write(header.tobytes())
        f.write(b"\xaa" * 128)
        f.write(img.tobytes())
    np.testing.assert_array_equal(mrc_io.read_mrc(path), img)


def test_mrc_rejects_garbage(tmp_path):
    path = str(tmp_path / "bad.mrc")
    with open(path, "wb") as f:
        f.write(b"not an mrc file")
    with pytest.raises(mrc_io.MrcError):
        mrc_io.read_header(path)
    assert not mrc_io.is_single_frame_micrograph(path)


def test_is_single_frame(tmp_path):
    p2d = str(tmp_path / "a.mrc")
    mrc_io.write_mrc(p2d, np.zeros((4, 4), np.float32))
    p3d = str(tmp_path / "b.mrc")
    mrc_io.write_mrc(p3d, np.zeros((3, 4, 4), np.float32))
    assert mrc_io.is_single_frame_micrograph(p2d)
    assert not mrc_io.is_single_frame_micrograph(p3d)


# ------------------------- subsets -------------------------


def _fake_data(n, seed=1):
    rng = np.random.default_rng(seed)
    return [(f"mic_{i:03d}.mrc", float(d))
            for i, d in enumerate(rng.uniform(1e4, 4e4, n))]


def test_tertile_split_partitions():
    data = _fake_data(50)
    low, med, high = subsets.tertile_split(data)
    assert len(low) + len(med) + len(high) == 50
    assert max(d for _, d in low) <= min(d for _, d in med)
    assert max(d for _, d in med) <= min(d for _, d in high)


def test_calc_subsets_monotone():
    d = subsets.calc_subsets(60)
    assert d[100] == 60
    vals = list(d.values())
    assert vals == sorted(vals)
    for tgt, s in d.items():
        if tgt != 100:
            assert s / 60 * 100 <= tgt


def test_split_dataset_partition_and_determinism():
    data = _fake_data(60)
    t1, v1, te1, sub1 = subsets.split_dataset(data)
    t2, v2, te2, _ = subsets.split_dataset(data)
    assert (t1, v1, te1) == (t2, v2, te2)
    assert len(t1) == round(0.2 * 60)
    assert len(v1) == 6
    assert len(t1) + len(v1) + len(te1) == 60
    names = [f for f, _ in t1 + v1 + te1]
    assert len(set(names)) == 60
    # train spans the defocus distribution: all three tertiles present
    low, med, high = subsets.tertile_split(data)
    for tert in (low, med, high):
        tert_names = {f for f, _ in tert}
        assert tert_names & set(f for f, _ in t1)


def test_split_dataset_ignore_test():
    data = _fake_data(30)
    train, val, test, sub = subsets.split_dataset(data, ignore_test=True)
    assert test == []
    assert len(train) == 30 - 6
    assert list(sub.keys()) == [100]


def test_cli_end_to_end(tmp_path):
    box_dir = tmp_path / "box"
    mrc_dir = tmp_path / "mrc"
    out_dir = tmp_path / "out"
    box_dir.mkdir(), mrc_dir.mkdir()
    n = 40
    defocus_lines = []
    rng = np.random.default_rng(2)
    for i in range(n):
        base = f"mic_{i:03d}"
        mrc_io.write_mrc(
            str(mrc_dir / f"{base}.mrc"), np.zeros((8, 8), np.float32)
        )
        (box_dir / f"{base}.box").write_text("1\t1\t4\t4\t0.5\n")
        d = rng.uniform(1e4, 4e4)
        defocus_lines.append(f"{base}.mrc\t{d:.1f}\t{d:.1f}")
    defocus_file = tmp_path / "defocus.txt"
    defocus_file.write_text("\n".join(defocus_lines) + "\n")

    from repic_tpu.main import build_parser

    args = build_parser().parse_args(
        ["build_subsets", str(defocus_file), str(box_dir),
         str(mrc_dir), str(out_dir)]
    )
    args.func(args)

    train_100 = out_dir / "train" / "train_100"
    assert train_100.is_dir()
    mrcs = [f for f in os.listdir(train_100) if f.endswith(".mrc")]
    boxes = [f for f in os.listdir(train_100) if f.endswith(".box")]
    assert len(mrcs) == round(0.2 * n)
    assert len(boxes) == len(mrcs)
    assert all(os.path.islink(train_100 / f) for f in mrcs)
    assert len(os.listdir(out_dir / "val")) == 2 * 6
    test_n = len(
        [f for f in os.listdir(out_dir / "test") if f.endswith(".mrc")]
    )
    assert test_n == n - round(0.2 * n) - 6
    # defocus plot written next to the defocus file
    assert (tmp_path / "defocus.png").is_file()


def test_cli_fallback_scan_without_defocus(tmp_path, capsys):
    box_dir = tmp_path / "box"
    mrc_dir = tmp_path / "mrc"
    box_dir.mkdir(), mrc_dir.mkdir()
    for i in range(12):
        mrc_io.write_mrc(
            str(mrc_dir / f"m{i}.mrc"), np.zeros((4, 4), np.float32)
        )
    (mrc_dir / "junk.txt").write_text("nope")
    from repic_tpu.main import build_parser

    args = build_parser().parse_args(
        ["build_subsets", str(tmp_path / "missing.txt"), str(box_dir),
         str(mrc_dir), str(tmp_path / "out"), "--ignore_test"]
    )
    args.func(args)
    out = capsys.readouterr().out
    assert "12 valid MRC files found" in out
    assert (tmp_path / "out" / "train").is_dir()
