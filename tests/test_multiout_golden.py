"""Golden test for the --multi_out phase-1 path vs the executed
reference.

``tests/golden/ref_multiout_10017_2mics.json`` was produced by
executing reference ``get_cliques --multi_out`` on a 2-micrograph
subset of examples/10017 (clique rows per picker, conf-0 singleton
re-add candidates, constraint-matrix shape).

Two deliberate identity differences:

* particle ids — reference: global mutable ``box_id`` counter; here:
  deterministic positional ids;
* member-to-picker-column labels — the reference mislabels them.
  ``add_nodes_to_graph`` is invoked with the FULL picker list for
  every pair (get_cliques.py:143 passes ``methods``, not the pair's
  labels), so ``node_names[0]/[1]`` tag e.g. every topaz node as
  'deepPicker' and the final attribute depends on pair-processing
  order.  Sorting clique members "by picker name" then assigns
  coordinates to the wrong columns.  Our columns are correct (each
  slot's coordinate really comes from that picker's BOX file), which
  ``test_our_multiout_labels_are_truthful`` verifies and
  ``test_reference_multiout_labels_are_mislabeled`` pins as a
  reference defect.

The golden comparison is therefore label-AGNOSTIC: clique coordinate
sets, weights, singleton coordinates, and matrix structure.
"""

import json
import os
import pickle
import shutil
from types import SimpleNamespace

import numpy as np
import pytest

from tests.conftest import REFERENCE_EXAMPLES, needs_reference

GOLDEN = os.path.join(
    os.path.dirname(__file__), "golden", "ref_multiout_10017_2mics.json"
)
NAMES = (
    "Falcon_2012_06_12-14_33_35_0",
    "Falcon_2012_06_12-15_17_31_0",
)


def _stage_subset(tmp_path):
    stage = tmp_path / "in"
    for p in os.listdir(REFERENCE_EXAMPLES):
        src = os.path.join(REFERENCE_EXAMPLES, p)
        if not os.path.isdir(src):
            continue
        (stage / p).mkdir(parents=True)
        for n in NAMES:
            shutil.copy(os.path.join(src, n + ".box"), stage / p)
    return str(stage)


@pytest.fixture(scope="module")
def ours(tmp_path_factory):
    from repic_tpu.commands import get_cliques

    if not os.path.isdir(REFERENCE_EXAMPLES):
        pytest.skip("reference example data not mounted")
    tmp_path = tmp_path_factory.mktemp("mo")
    out = str(tmp_path / "out")
    get_cliques.main(
        SimpleNamespace(
            in_dir=_stage_subset(tmp_path),
            out_dir=out,
            box_size=180,
            multi_out=True,
            get_cc=False,
            max_neighbors=16,
            no_mesh=True,
        )
    )
    result = {}
    for name in NAMES:
        with open(
            os.path.join(out, name + "_consensus_coords.pickle"), "rb"
        ) as f:
            coords = pickle.load(f)
        with open(
            os.path.join(out, name + "_weight_vector.pickle"), "rb"
        ) as f:
            w = np.asarray(pickle.load(f))
        with open(
            os.path.join(out, name + "_constraint_matrix.pickle"), "rb"
        ) as f:
            a_mat = pickle.load(f)
        result[name] = (coords, w, a_mat)
    return result


def _split_rows(labels, rows):
    cliques, singletons = [], []
    for r in rows:
        filled = [(labels[i], v) for i, v in enumerate(r) if v]
        if len(filled) == len(labels):
            cliques.append(filled)
        else:
            ((lab, v),) = filled
            singletons.append((lab, v))
    return cliques, singletons


def _coord_key(members):
    """Label-agnostic clique identity: the set of (x, y) coords."""
    return frozenset(
        (round(float(v[0]), 3), round(float(v[1]), 3))
        for _, v in members
    )


@needs_reference
def test_multi_out_matches_reference_label_agnostic(ours):
    with open(GOLDEN) as f:
        golden = json.load(f)
    for name, gd in golden.items():
        coords, w, a_mat = ours[name]
        labels = coords[0]
        assert sorted(labels) == sorted(gd["labels"])
        cliques, singles = _split_rows(labels, coords[1:])
        mine = [_coord_key(c) for c in cliques]
        want = [
            frozenset(
                (round(xy[0], 3), round(xy[1], 3))
                for xy in c.values()
            )
            for c in gd["cliques"]
        ]
        assert len(mine) == len(want)
        assert set(mine) == set(want), f"{name}: clique coords"
        mine_w = dict(zip(mine, w))
        want_w = dict(zip(want, gd["weights"]))
        for key in want_w:
            np.testing.assert_allclose(
                mine_w[key], want_w[key], atol=1e-4, err_msg=name
            )
        # Singleton semantics: the reference INTENDS "particles not in
        # any clique" but its set difference compares 3-tuple graph
        # nodes against raw coordinate records, which never match
        # (get_cliques.py:210-213) — so it re-adds EVERY particle.
        # Ours writes the intended non-clique set.  The final run_ilp
        # multi-out TSV is identical either way (its re-add pass
        # recomputes membership from all rows), so the pickles are
        # compared against their respective documented semantics.
        raw = {
            lab: {
                (round(float(x), 3), round(float(y), 3))
                for x, y in np.loadtxt(
                    os.path.join(
                        REFERENCE_EXAMPLES, lab, name + ".box"
                    ),
                    usecols=(0, 1),
                )
            }
            for lab in labels
        }
        # the singleton COLUMNS are correctly labeled on both sides
        # (the reference's j-loop indexes methods directly)
        want_singles = {lab: set() for lab in labels}
        for lab, x, y in gd["singletons"]:
            want_singles[lab].add((round(x, 3), round(y, 3)))
        mine_singles = {lab: set() for lab in labels}
        for lab, v in singles:
            mine_singles[lab].add(
                (round(float(v[0]), 3), round(float(v[1]), 3))
            )
        # ours labels its clique slots truthfully, so per-picker
        # clique participation is recoverable from our rows
        mine_members = {lab: set() for lab in labels}
        for members in cliques:
            for lab, v in members:
                mine_members[lab].add(
                    (round(float(v[0]), 3), round(float(v[1]), 3))
                )
        for lab in labels:
            assert want_singles[lab] == raw[lab], (
                f"{name}/{lab}: reference re-adds every particle"
            )
            assert (
                mine_singles[lab] == raw[lab] - mine_members[lab]
            ), f"{name}/{lab}: ours re-adds the non-clique particles"
        assert a_mat.shape == (gd["n_vertices"], gd["n_cliques_cols"])
        assert a_mat.nnz == gd["nnz"]


@needs_reference
def test_our_multiout_labels_are_truthful(ours):
    """Every clique slot's coordinate must exist in THAT picker's BOX
    file (the property the reference's multi_out violates)."""
    for name in NAMES:
        coords, _, _ = ours[name]
        labels = coords[0]
        raw = {
            lab: {
                tuple(np.round(row[:2], 1))
                for row in np.loadtxt(
                    os.path.join(REFERENCE_EXAMPLES, lab, name + ".box"),
                    usecols=(0, 1),
                )
            }
            for lab in labels
        }
        cliques, singles = _split_rows(labels, coords[1:])
        for members in cliques:
            for lab, v in members:
                key = (round(float(v[0]), 1), round(float(v[1]), 1))
                assert key in raw[lab], f"{name}: {lab} {key}"
        for lab, v in singles:
            key = (round(float(v[0]), 1), round(float(v[1]), 1))
            assert key in raw[lab], f"{name}: singleton {lab} {key}"


@needs_reference
def test_reference_multiout_labels_are_mislabeled():
    """Pin the reference defect: at least one golden clique slot holds
    a coordinate that is NOT in that picker's BOX file.  If a fixed
    reference regenerates the golden, this starts failing — signal to
    switch the golden comparison to exact column equality."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    name = NAMES[0]
    gd = golden[name]
    raw = {
        lab: {
            tuple(np.round(row[:2], 1))
            for row in np.loadtxt(
                os.path.join(REFERENCE_EXAMPLES, lab, name + ".box"),
                usecols=(0, 1),
            )
        }
        for lab in gd["labels"]
    }
    mislabeled = sum(
        1
        for c in gd["cliques"]
        for lab, xy in c.items()
        if (round(xy[0], 1), round(xy[1], 1)) not in raw[lab]
    )
    assert mislabeled > 0


@needs_reference
def test_cc_stats_match_reference(tmp_path):
    """Largest-CC size and CC count in the runtime TSV, vs values the
    executed reference printed for the same 2-micrograph subset
    (reference get_cliques.py:146-149; columns: runtime, largest CC,
    num CC)."""
    from repic_tpu.commands import get_cliques

    want = {NAMES[0]: (16, 563), NAMES[1]: (12, 525)}
    out = str(tmp_path / "out")
    get_cliques.main(
        SimpleNamespace(
            in_dir=_stage_subset(tmp_path),
            out_dir=out,
            box_size=180,
            multi_out=False,
            get_cc=False,
            max_neighbors=16,
            no_mesh=True,
        )
    )
    for name, (largest, num) in want.items():
        line = open(
            os.path.join(out, name + "_runtime.tsv")
        ).read().split()
        assert int(float(line[1])) == largest, name
        assert int(float(line[2])) == num, name


@needs_reference
def test_get_cc_filter_matches_reference(tmp_path):
    """--get_cc (keep only the largest connected component's cliques):
    representative coordinates and weight sum vs the executed
    reference on the same subset
    (tests/golden/ref_getcc_10017_2mics.json)."""
    from repic_tpu.commands import get_cliques

    golden_path = os.path.join(
        os.path.dirname(__file__), "golden", "ref_getcc_10017_2mics.json"
    )
    with open(golden_path) as f:
        golden = json.load(f)
    out = str(tmp_path / "out")
    get_cliques.main(
        SimpleNamespace(
            in_dir=_stage_subset(tmp_path),
            out_dir=out,
            box_size=180,
            multi_out=False,
            get_cc=True,
            max_neighbors=16,
            no_mesh=True,
        )
    )
    for name, gd in golden.items():
        with open(
            os.path.join(out, name + "_consensus_coords.pickle"), "rb"
        ) as f:
            coords = pickle.load(f)
        with open(
            os.path.join(out, name + "_weight_vector.pickle"), "rb"
        ) as f:
            w = np.asarray(pickle.load(f))
        assert len(coords) == gd["n"], name
        mine = sorted(
            [round(float(c[0]), 3), round(float(c[1]), 3)]
            for c in coords
        )
        assert mine == gd["rep_xy"], f"{name}: representative coords"
        np.testing.assert_allclose(
            float(np.sum(w)), gd["w_sum"], atol=2e-3, err_msg=name
        )
