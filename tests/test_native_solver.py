"""Native C++ exact solver: parity with the Python oracle and with
brute force, plus scale beyond what the Python oracle handles quickly."""

import itertools

import numpy as np
import pytest

from repic_tpu import native
from repic_tpu.ops.solver import solve_exact, solve_exact_py

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="no C++ toolchain"
)


def brute_force_value(member_vertex, w):
    best = -1.0
    n = len(w)
    for bits in itertools.product([0, 1], repeat=n):
        used = set()
        ok, val = True, 0.0
        for c in range(n):
            if bits[c]:
                verts = set(int(v) for v in member_vertex[c])
                if used & verts:
                    ok = False
                    break
                used |= verts
                val += w[c]
        if ok and val > best:
            best = val
    return best


def random_instance(rng, n_cliques, k, n_vertices):
    mv = rng.integers(0, n_vertices, size=(n_cliques, k)).astype(np.int32)
    w = rng.uniform(0.01, 1.0, size=n_cliques)
    return mv, w


def test_native_matches_brute_force(rng):
    for _ in range(10):
        mv, w = random_instance(rng, 12, 3, 10)
        got = native.solve_exact_native(mv, w)
        assert got is not None
        np.testing.assert_allclose(
            w[got].sum(), brute_force_value(mv, w), rtol=1e-9
        )


def test_native_matches_python_oracle(rng):
    for _ in range(10):
        mv, w = random_instance(rng, 60, 3, 40)
        got = native.solve_exact_native(mv, w)
        want = solve_exact_py(mv, w)
        np.testing.assert_allclose(w[got].sum(), w[want].sum(), rtol=1e-9)


def test_native_solution_feasible(rng):
    mv, w = random_instance(rng, 200, 3, 120)
    got = native.solve_exact_native(mv, w)
    sel = [set(int(v) for v in row) for row in mv[got]]
    for a, b in itertools.combinations(sel, 2):
        assert not (a & b)


def test_native_empty():
    got = native.solve_exact_native(
        np.zeros((0, 3), np.int32), np.zeros(0)
    )
    assert got is not None and got.shape == (0,)


def test_dispatcher_prefers_native(rng):
    mv, w = random_instance(rng, 30, 3, 20)
    got = solve_exact(mv, w)
    want = solve_exact_py(mv, w)
    np.testing.assert_allclose(w[got].sum(), w[want].sum(), rtol=1e-9)


def test_native_chain_adversarial():
    mv = np.array([[0, 1, 2], [2, 3, 4], [4, 5, 6]], np.int32)
    w = np.array([0.6, 1.0, 0.6])
    got = native.solve_exact_native(mv, w)
    assert list(got) == [True, False, True]


def test_native_scale_smoke(rng):
    # A size the pure-Python oracle would crawl through: 5k cliques in
    # loosely-coupled local clusters (the realistic dense-micrograph
    # shape).  Must finish fast and beat/equal greedy.
    import time

    n_clusters, per = 250, 20
    mvs, ws = [], []
    for c in range(n_clusters):
        base = c * 30
        mv = rng.integers(base, base + 25, size=(per, 3)).astype(np.int32)
        mvs.append(mv)
        ws.append(rng.uniform(0.01, 1.0, size=per))
    mv = np.concatenate(mvs)
    w = np.concatenate(ws)
    t0 = time.time()
    got = native.solve_exact_native(mv, w)
    assert time.time() - t0 < 10.0
    sel = [set(int(v) for v in row) for row in mv[got]]
    for a, b in itertools.combinations(sel, 2):
        assert not (a & b)


def test_native_rejects_negative_ids():
    mv = np.array([[0, -1, 2]], np.int32)
    with pytest.raises(ValueError):
        native.solve_exact_native(mv, np.array([1.0]))


def test_native_deep_chain_no_stack_overflow():
    # One long conflict chain => a single component whose exact search
    # depth equals its size; the iterative DFS must handle it.
    n = 30_000
    mv = np.stack(
        [np.arange(n), np.arange(n) + 1, np.arange(n) + n + 10], axis=1
    ).astype(np.int32)
    mv[:, 1] = np.arange(n) + 1  # chain: clique i conflicts with i+1
    w = np.ones(n)
    got = native.solve_exact_native(mv, w, node_limit=500_000)
    assert got is not None
    # alternating selection is optimal for a unit-weight chain
    assert got.sum() == (n + 1) // 2


def test_load_builds_outside_module_lock(monkeypatch):
    """RT303 sweep regression: the (up to 120 s) g++ compile must not
    run while holding the module cache lock — a concurrent load of a
    DIFFERENT stem must only contend for the tiny dict sections."""
    seen = {}

    def fake_build(stem, force=False):
        seen["locked_during_build"] = native._LOCK.locked()
        return None

    monkeypatch.setattr(native, "_build", fake_build)
    monkeypatch.setattr(native, "_LIBS", {})
    monkeypatch.setattr(native, "_STEM_LOCKS", {})
    assert native._load("stem_x", lambda lib: None) is None
    assert seen["locked_during_build"] is False
    # the failure is cached: a second load never re-builds
    seen.clear()
    assert native._load("stem_x", lambda lib: None) is None
    assert not seen
    # and each stem serializes on its own lock
    assert set(native._STEM_LOCKS) == {"stem_x"}
