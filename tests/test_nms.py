"""Device NMS vs the host greedy loop: bit-identical keep sets.

The host loop in models/infer.peak_detection is the semantic
specification (golden-gated against the executed reference in
tests/test_deeppicker_golden.py); ops/nms.py re-expresses it as a
device ``fori_loop``.  These tests sweep random clustered candidate
sets — including score ties and chained kills — and require exact
equality between the two paths.
"""

import numpy as np
import pytest

from repic_tpu.models.infer import peak_detection
from repic_tpu.ops.nms import greedy_suppress_device


def _host_keep(yx, scores, thr):
    """The host loop, extracted verbatim semantics."""
    order = np.arange(len(yx))
    dead = np.zeros(len(yx), bool)
    for i in order[:-1]:
        if dead[i]:
            continue
        rest = order[i + 1:]
        rest = rest[~dead[rest]]
        if len(rest) == 0:
            break
        d = np.hypot(yx[i, 0] - yx[rest, 0], yx[i, 1] - yx[rest, 1])
        close = rest[d < thr]
        if len(close) == 0:
            continue
        stronger = scores[close] > scores[i]
        if stronger.any():
            cut = int(np.argmax(stronger))
            dead[close[:cut]] = True
            dead[i] = True
        else:
            dead[close] = True
    return ~dead


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("n", [3, 50, 400])
def test_device_matches_host_random(seed, n):
    rng = np.random.default_rng(seed)
    # clustered coordinates force dense conflict chains
    centers = rng.integers(0, 120, size=(max(n // 8, 1), 2))
    yx = (
        centers[rng.integers(0, len(centers), n)]
        + rng.integers(-4, 5, size=(n, 2))
    ).clip(0)
    scores = rng.standard_normal(n).astype(np.float32)
    window = 7
    thr = window / 2.0
    got = greedy_suppress_device(yx, scores, thr)
    want = _host_keep(yx, scores.astype(np.float64), thr)
    assert np.array_equal(got, want)


def test_device_matches_host_with_ties():
    """Equal scores: later candidate is weaker-or-equal -> killed."""
    yx = np.array([[0, 0], [0, 1], [0, 2], [10, 10]])
    scores = np.array([1.0, 1.0, 2.0, 1.0], np.float32)
    thr = 3.5 / 2
    got = greedy_suppress_device(yx, scores, thr)
    want = _host_keep(yx, scores, thr)
    assert np.array_equal(got, want)


def test_kill_chain_partial_survival():
    """A stronger later neighbor kills i but spares i's later weak
    neighbors beyond it (the reference's early-break semantics)."""
    # i=0 sees j=1 (weaker: killed), j=2 (stronger: kills 0, stop);
    # j=3 (weak, close to 0) must SURVIVE 0's pass and then lose to 2.
    yx = np.array([[0, 0], [0, 1], [0, 2], [1, 0]])
    scores = np.array([2.0, 1.0, 3.0, 1.5], np.float32)
    thr = 5.0
    want = _host_keep(yx, scores, thr)
    got = greedy_suppress_device(yx, scores, thr)
    assert np.array_equal(got, want)
    assert want.tolist() == [False, False, True, False]


def test_empty_and_single():
    assert greedy_suppress_device(
        np.zeros((0, 2), int), np.zeros(0), 2.0
    ).shape == (0,)
    assert greedy_suppress_device(
        np.array([[5, 5]]), np.array([1.0]), 2.0
    ).tolist() == [True]


def test_peak_detection_device_flag_equivalence():
    """Full peak_detection with device_nms forced on == host path."""
    rng = np.random.default_rng(3)
    smap = rng.random((80, 80)).astype(np.float32)
    # smooth to create plateaus and realistic maxima
    k = np.ones((3, 3)) / 9.0
    from scipy import ndimage

    smap = ndimage.convolve(smap, k, mode="nearest")
    host = peak_detection(smap, window=5, device_nms=False)
    dev = peak_detection(smap, window=5, device_nms=True)
    assert np.allclose(host, dev)


def test_coordinate_limit_guard():
    """Grids beyond the exact-int32 bound refuse the device path
    (and peak_detection's auto mode must route them to the host)."""
    from repic_tpu.ops.nms import COORD_LIMIT

    yx = np.array([[0, 0], [COORD_LIMIT + 10, 0]])
    with pytest.raises(ValueError, match="host path"):
        greedy_suppress_device(yx, np.array([1.0, 2.0]), 2.0)
