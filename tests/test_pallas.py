"""Pallas fused neighbor-search kernel tests (interpret mode on CPU;
the same kernel compiles for real on TPU).

The kernel must reproduce the dense ``top_k(pairwise_iou_matrix)``
neighbor search exactly: same top-D value sets, indices that point at
the right candidates, and the same above-threshold adjacency counts —
including masked particles, padding to tile multiples, and mixed box
sizes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from repic_tpu.ops.cliques import enumerate_cliques
from repic_tpu.ops.iou import pairwise_iou_matrix
from repic_tpu.ops.iou_pallas import pallas_topk_neighbors

BOX = 180.0


def _sets(rng, n, m, extent=2000.0):
    xa = jnp.asarray(rng.uniform(0, extent, (n, 2)), jnp.float32)
    xb = jnp.asarray(rng.uniform(0, extent, (m, 2)), jnp.float32)
    ma = jnp.asarray(rng.uniform(size=n) > 0.15)
    mb = jnp.asarray(rng.uniform(size=m) > 0.15)
    return xa, ma, xb, mb


@pytest.mark.parametrize("n,m", [(200, 300), (64, 64), (130, 257)])
def test_pallas_matches_dense_topk(n, m):
    rng = np.random.default_rng(n + m)
    xa, ma, xb, mb = _sets(rng, n, m)
    tv, ti, cnt = pallas_topk_neighbors(
        xa, ma, xb, mb, BOX, BOX, d=8, tile_m=64, tile_n=128,
        interpret=True,
    )
    ref = pairwise_iou_matrix(xa, ma, xb, mb, BOX)
    rv, _ = jax.lax.top_k(ref, 8)
    np.testing.assert_allclose(
        np.where(np.asarray(tv) < 0, 0.0, np.asarray(tv)),
        np.asarray(rv),
        atol=1e-6,
    )
    np.testing.assert_array_equal(
        np.asarray(cnt), np.sum(np.asarray(ref) > 0.3, axis=1)
    )
    # every returned index points at a candidate with that IoU
    refn, tvn, tin = np.asarray(ref), np.asarray(tv), np.asarray(ti)
    for i in range(n):
        for v, ix in zip(tvn[i], tin[i]):
            if v > 1e-6:
                assert ix < m
                np.testing.assert_allclose(refn[i, ix], v, atol=1e-6)
            else:
                # empty slots carry the sentinel index
                assert v <= 0


def test_pallas_mixed_sizes_traced():
    """Sizes ride through SMEM, so traced (jit-argument) scalars and
    per-set mixed sizes both work."""
    rng = np.random.default_rng(3)
    xa, ma, xb, mb = _sets(rng, 96, 96)

    @jax.jit
    def run(sa, sb):
        return pallas_topk_neighbors(
            xa, ma, xb, mb, sa, sb, d=4, tile_m=32, tile_n=64,
            interpret=True,
        )

    tv, ti, cnt = run(jnp.float32(150.0), jnp.float32(210.0))
    ref = pairwise_iou_matrix(xa, ma, xb, mb, 150.0, 210.0)
    rv, _ = jax.lax.top_k(ref, 4)
    np.testing.assert_allclose(
        np.where(np.asarray(tv) < 0, 0.0, np.asarray(tv)),
        np.asarray(rv),
        atol=1e-6,
    )


def test_enumerate_cliques_pallas_matches():
    """The full enumeration agrees between the XLA and Pallas
    neighbor-search front ends."""
    rng = np.random.default_rng(5)
    base = rng.uniform(0, 3000, (120, 2))
    xy = jnp.asarray(
        np.stack([base + rng.normal(0, 25, base.shape) for _ in range(3)]),
        jnp.float32,
    )
    conf = jnp.asarray(rng.uniform(0.1, 1, (3, 120)), jnp.float32)
    mask = jnp.asarray(rng.uniform(size=(3, 120)) > 0.1)
    dense = enumerate_cliques(xy, conf, mask, BOX, max_neighbors=8)
    pallas = enumerate_cliques(
        xy, conf, mask, BOX, max_neighbors=8, use_pallas=True
    )
    dk = {
        tuple(mm)
        for mm, v in zip(
            np.asarray(dense.member_idx), np.asarray(dense.valid)
        )
        if v
    }
    pk = {
        tuple(mm)
        for mm, v in zip(
            np.asarray(pallas.member_idx), np.asarray(pallas.valid)
        )
        if v
    }
    assert dk == pk
    assert int(dense.max_adjacency) == int(pallas.max_adjacency)


def test_batched_pipeline_with_pallas(tmp_path):
    """The vmapped/batched consensus runs with the Pallas front end
    and matches the XLA front end's picks."""
    from repic_tpu.parallel.batching import pad_batch
    from repic_tpu.pipeline.consensus import run_consensus_batch
    from repic_tpu.utils.box_io import BoxSet

    rng = np.random.default_rng(9)
    loaded = []
    for i in range(2):
        base = rng.uniform(0, 2500, (80, 2))
        sets = [
            BoxSet(
                xy=(base + rng.normal(0, 20, base.shape)).astype(
                    np.float32
                ),
                conf=rng.uniform(0.1, 1, 80).astype(np.float32),
                wh=np.full((80, 2), BOX, np.float32),
            )
            for _ in range(3)
        ]
        loaded.append((f"m{i}", sets))
    batch = pad_batch(loaded)
    plain = run_consensus_batch(batch, BOX, use_mesh=False)
    fused = run_consensus_batch(
        batch, BOX, use_mesh=False, use_pallas=True
    )
    for i in range(2):
        a = {
            tuple(mm)
            for mm, p in zip(
                np.asarray(plain.member_idx[i]),
                np.asarray(plain.picked[i]),
            )
            if p
        }
        b = {
            tuple(mm)
            for mm, p in zip(
                np.asarray(fused.member_idx[i]),
                np.asarray(fused.picked[i]),
            )
            if p
        }
        assert a == b


def test_pallas_ignored_on_spatial_path_warns():
    """--pallas + spatial path: warn (ADVICE r1), never silently drop."""
    import pytest

    from repic_tpu.parallel.batching import pad_batch
    from repic_tpu.pipeline.consensus import run_consensus_batch
    from repic_tpu.utils.box_io import BoxSet

    rng = np.random.default_rng(21)
    sets = [
        BoxSet(
            xy=rng.uniform(0, 2000, size=(60, 2)).astype(np.float32),
            conf=rng.uniform(0.1, 1, 60).astype(np.float32),
            wh=np.full((60, 2), BOX, np.float32),
        )
        for _ in range(3)
    ]
    batch = pad_batch([("m0", sets)])
    with pytest.warns(UserWarning, match="Pallas.*ignored|ignored"):
        run_consensus_batch(
            batch, BOX, use_mesh=False, spatial=True, use_pallas=True
        )


@pytest.mark.tpu
def test_pallas_compiled_on_tpu_matches_interpret():
    """Real-TPU smoke test for the compiled (non-interpret) kernel —
    verifies the lane-aligned block layout actually lowers and matches
    interpret-mode output.  Run manually with:
        REPIC_TPU_TEST_TPU=1 pytest -m tpu tests/test_pallas.py
    (without that env var the conftest forces CPU and this skips)."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU backend")
    rng = np.random.default_rng(3)
    n, m = 300, 400
    xa = jnp.asarray(rng.uniform(0, 2000, size=(n, 2)), jnp.float32)
    xb = jnp.asarray(rng.uniform(0, 2000, size=(m, 2)), jnp.float32)
    ma = jnp.asarray(rng.uniform(size=n) > 0.1)
    mb = jnp.asarray(rng.uniform(size=m) > 0.1)
    compiled = pallas_topk_neighbors(
        xa, ma, xb, mb, BOX, BOX, d=8, interpret=False
    )
    interp = pallas_topk_neighbors(
        xa, ma, xb, mb, BOX, BOX, d=8, interpret=True
    )
    for c, i in zip(compiled, interp):
        np.testing.assert_allclose(
            np.asarray(c), np.asarray(i), atol=1e-6
        )


def test_empty_candidate_set_early_return():
    """m=0 must return sentinel/NEG outputs, not uninitialized
    buffers (code-review r2 finding)."""
    xa = jnp.asarray(np.random.default_rng(0).uniform(0, 100, (5, 2)),
                     jnp.float32)
    ma = jnp.ones(5, bool)
    xb = jnp.zeros((0, 2), jnp.float32)
    mb = jnp.zeros((0,), bool)
    v, i, adj = pallas_topk_neighbors(
        xa, ma, xb, mb, BOX, BOX, d=4, interpret=True
    )
    assert v.shape == (5, 4) and (np.asarray(v) == -1.0).all()
    assert (np.asarray(i) == 0).all()  # sentinel M == 0
    assert (np.asarray(adj) == 0).all()


@pytest.mark.parametrize("d", [128, 200])
def test_multi_block_top_d_state_matches_dense(d):
    """d >= 128 spans multiple 128-lane state blocks (the old layout's
    hard limit); values, counts, and index validity must still match
    the dense matrix path exactly."""
    rng = np.random.default_rng(d)
    xa, ma, xb, mb = _sets(rng, 96, 320, extent=900.0)
    tv, ti, cnt = pallas_topk_neighbors(
        xa, ma, xb, mb, BOX, BOX, d=d, tile_m=32, tile_n=128,
        interpret=True,
    )
    assert tv.shape == (96, d) and ti.shape == (96, d)
    ref = pairwise_iou_matrix(xa, ma, xb, mb, BOX)
    rv, _ = jax.lax.top_k(ref, d)
    np.testing.assert_allclose(
        np.where(np.asarray(tv) < 0, 0.0, np.asarray(tv)),
        np.asarray(rv),
        atol=1e-6,
    )
    np.testing.assert_array_equal(
        np.asarray(cnt), np.sum(np.asarray(ref) > 0.3, axis=1)
    )
    refn, tvn, tin = np.asarray(ref), np.asarray(tv), np.asarray(ti)
    for i in range(0, 96, 7):
        for v, ix in zip(tvn[i], tin[i]):
            if v > 1e-6:
                assert ix < 320
                np.testing.assert_allclose(refn[i, ix], v, atol=1e-6)


def test_d128_stays_on_pallas_and_matches():
    """D=128 (the old fallback point) now runs the widened kernel; the
    clique set must equal the matrix path's."""
    rng = np.random.default_rng(5)
    n = 160
    xy = jnp.asarray(rng.uniform(0, 800, size=(2, n, 2)), jnp.float32)
    conf = jnp.ones((2, n), jnp.float32)
    mask = jnp.ones((2, n), bool)
    cs = enumerate_cliques(
        xy, conf, mask, BOX, max_neighbors=128, use_pallas=True
    )
    ref = enumerate_cliques(
        xy, conf, mask, BOX, max_neighbors=128, use_pallas=False
    )
    assert int(cs.num_valid) == int(ref.num_valid)


def test_past_cap_d_falls_back_to_xla_with_warning():
    """Past _PALLAS_MAX_D the matrix path takes over — loudly."""
    rng = np.random.default_rng(6)
    n = 300
    xy = jnp.asarray(rng.uniform(0, 800, size=(2, n, 2)), jnp.float32)
    conf = jnp.ones((2, n), jnp.float32)
    mask = jnp.ones((2, n), bool)
    with pytest.warns(UserWarning, match="exceeds the Pallas"):
        cs = enumerate_cliques(
            xy, conf, mask, BOX, max_neighbors=257, use_pallas=True
        )
    ref = enumerate_cliques(
        xy, conf, mask, BOX, max_neighbors=257, use_pallas=False
    )
    assert int(cs.num_valid) == int(ref.num_valid)
