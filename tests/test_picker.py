"""CNN picker tests: preprocessing oracles, patch/FCN weight-sharing
parity, peak detection vs a scipy oracle of the reference algorithm,
checkpoint round-trip, and the pick CLI end-to-end."""

import math
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from repic_tpu.models import preprocess as pp
from repic_tpu.models.cnn import (
    PickerCNN,
    PickerFCN,
    fc_params_as_conv,
)
from repic_tpu.models import infer
from repic_tpu.models.checkpoint import load_checkpoint, save_checkpoint


@pytest.fixture(scope="module")
def params():
    model = PickerCNN()
    return model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 1))
    )["params"]


# ---------------------------------------------------------------- preprocess


def test_bin2d_matches_numpy_oracle(rng):
    img = rng.normal(size=(17, 23)).astype(np.float32)
    got = np.asarray(pp.bin2d(jnp.asarray(img), 3))
    want = np.zeros((5, 7), np.float32)
    for i in range(5):
        for j in range(7):
            want[i, j] = img[3 * i : 3 * i + 3, 3 * j : 3 * j + 3].mean()
    # atol matters: a 3x3 mean of standard normals can land arbitrarily
    # close to zero, where any pure-rtol comparison of two differently
    # associated float32 sums flakes (seen once in a full-suite run
    # where the shared rng stream happened to produce such a cell)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_gaussian_sigma01_is_identity(rng):
    # scipy truncates at radius int(4*0.1+0.5)=0 => identity
    img = rng.normal(size=(12, 12)).astype(np.float32)
    got = np.asarray(pp.gaussian_blur(jnp.asarray(img), 0.1))
    np.testing.assert_array_equal(got, img)


def test_gaussian_larger_sigma_matches_scipy(rng):
    scipy_ndimage = pytest.importorskip("scipy.ndimage")
    img = rng.normal(size=(32, 40)).astype(np.float32)
    got = np.asarray(pp.gaussian_blur(jnp.asarray(img), 1.5))
    want = scipy_ndimage.gaussian_filter(img, 1.5, mode="reflect")
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_bytescale_oracle(rng):
    patches = rng.normal(size=(5, 9, 9)).astype(np.float32) * 7
    got = np.asarray(pp.bytescale(jnp.asarray(patches)))
    for p, g in zip(patches, got):
        cmin, cmax = p.min(), p.max()
        want = np.floor(
            np.clip((p - cmin) * (255.0 / (cmax - cmin)), 0, 255) + 0.5
        )
        np.testing.assert_allclose(g, want)
    assert got.min() >= 0 and got.max() <= 255


def test_standardize_patches(rng):
    patches = rng.normal(size=(4, 8, 8)).astype(np.float32) * 3 + 5
    got = np.asarray(pp.standardize_patches(jnp.asarray(patches)))
    for g in got:
        assert abs(g.mean()) < 1e-5
        # unbiased std (ddof=1), matching the reference's torch.std
        assert abs(g.std(ddof=1) - 1) < 1e-4


def test_preprocess_micrograph_shapes(rng):
    img = rng.normal(size=(100, 130)).astype(np.float32)
    out = np.asarray(pp.preprocess_micrograph(jnp.asarray(img)))
    assert out.shape == (33, 43)
    assert abs(out.mean()) < 1e-5
    assert abs(out.std() - 1) < 1e-4


# ----------------------------------------------------------------- model


def test_cnn_output_shape(params):
    model = PickerCNN()
    out = model.apply({"params": params}, jnp.zeros((7, 64, 64, 1)))
    assert out.shape == (7, 2)


def test_fcn_matches_patch_classifier(params, rng):
    # Same weights, 64x64 input: FCN's single output == CNN logits.
    x = jnp.asarray(rng.normal(size=(3, 64, 64, 1)).astype(np.float32))
    cnn_logits = PickerCNN().apply({"params": params}, x)
    fcn_logits = PickerFCN().apply(
        {"params": fc_params_as_conv(params)}, x
    )
    np.testing.assert_allclose(
        np.asarray(cnn_logits),
        np.asarray(fcn_logits[:, 0, 0, :]),
        atol=1e-5,
    )


def test_fcn_stride16_grid(params, rng):
    # On a 96x96 input the FCN's (1,1) output equals the CNN applied
    # to the window starting at (16,16).
    x = jnp.asarray(rng.normal(size=(1, 96, 96, 1)).astype(np.float32))
    fcn_logits = PickerFCN().apply(
        {"params": fc_params_as_conv(params)}, x
    )
    assert fcn_logits.shape == (1, 3, 3, 2)
    want = PickerCNN().apply({"params": params}, x[:, 16:80, 16:80, :])
    np.testing.assert_allclose(
        np.asarray(fcn_logits[:, 1, 1, :]), np.asarray(want), atol=1e-4
    )


# ------------------------------------------------------------- peaks


def reference_peak_oracle(score_map, window):
    """Literal scipy transcription of the reference peak detection
    (autoPicker.py:62-131) used as the behavioral oracle."""
    from scipy import ndimage
    from scipy.ndimage import maximum_filter, minimum_filter

    data_max = maximum_filter(score_map, window)
    maxima = score_map == data_max
    data_min = minimum_filter(score_map, window)
    maxima[(data_max - data_min) <= 0] = False
    labeled, num = ndimage.label(maxima)
    yx = np.array(
        ndimage.center_of_mass(score_map, labeled, range(1, num + 1))
    ).astype(int)
    items = [
        [int(y), int(x), score_map[y, x], 0] for y, x in yx
    ]
    for i in range(len(items) - 1):
        if items[i][3] == 1:
            continue
        for j in range(i + 1, len(items)):
            if items[i][3] == 1:
                break
            if items[j][3] == 1:
                continue
            d = math.hypot(
                items[i][0] - items[j][0], items[i][1] - items[j][1]
            )
            if d < window / 2:
                if items[i][2] >= items[j][2]:
                    items[j][3] = 1
                else:
                    items[i][3] = 1
    return np.array(
        [[it[1], it[0], it[2]] for it in items if it[3] == 0],
        dtype=np.float64,
    ).reshape(-1, 3)


@pytest.mark.parametrize("window", [3, 5, 8, 9])
def test_peak_detection_matches_reference_oracle(rng, window):
    for _ in range(5):
        smap = rng.random((40, 50))
        got = infer.peak_detection(smap, window)
        want = reference_peak_oracle(smap, window)
        got_sorted = got[np.lexsort((got[:, 0], got[:, 1]))]
        want_sorted = want[np.lexsort((want[:, 0], want[:, 1]))]
        np.testing.assert_allclose(got_sorted, want_sorted)


def test_peak_detection_constant_map():
    assert len(infer.peak_detection(np.ones((20, 20)), 5)) == 0


def test_peak_detection_single_peak():
    smap = np.zeros((30, 30))
    smap[12, 17] = 1.0
    peaks = infer.peak_detection(smap, 5)
    assert len(peaks) == 1
    assert (peaks[0, 0], peaks[0, 1]) == (17, 12)


# ---------------------------------------------------------- end-to-end


def test_pick_micrograph_runs_both_modes(params, rng):
    raw = rng.normal(size=(400, 430)).astype(np.float32)
    for mode in ("patch", "fcn"):
        coords = infer.pick_micrograph(
            params, raw, particle_size=120, mode=mode
        )
        assert coords.shape[1] == 3
        if len(coords):
            # centers must lie inside the original micrograph
            assert coords[:, 0].min() >= 0
            assert coords[:, 0].max() <= 430
            assert coords[:, 1].max() <= 400


def test_checkpoint_roundtrip(params, tmp_path):
    path = str(tmp_path / "model.rptpu")
    meta = {"particle_size": 180, "patch_norm": "reference"}
    save_checkpoint(path, params, meta)
    params2, meta2 = load_checkpoint(path)
    assert meta2 == meta
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        params,
        params2,
    )


def test_checkpoint_bad_magic(tmp_path):
    path = str(tmp_path / "junk.rptpu")
    with open(path, "wb") as f:
        f.write(b"not a checkpoint")
    with pytest.raises(ValueError):
        load_checkpoint(path)


def test_pick_cli(params, tmp_path, rng):
    from repic_tpu.main import main as cli_main
    from repic_tpu.utils import mrc

    mrc_dir = tmp_path / "mrcs"
    out_dir = tmp_path / "out"
    mrc_dir.mkdir()
    for i in range(2):
        mrc.write_mrc(
            str(mrc_dir / f"mic{i}.mrc"),
            rng.normal(size=(400, 400)).astype(np.float32),
        )
    ckpt = str(tmp_path / "model.rptpu")
    save_checkpoint(
        ckpt, params, {"particle_size": 120, "patch_norm": "reference"}
    )
    cli_main(
        ["pick", ckpt, str(mrc_dir), str(out_dir), "--threshold", "0.0"]
    )
    # telemetry sinks (_events.jsonl, _metrics.*) live next to the
    # coordinate outputs now, like consensus run dirs
    boxes = sorted(
        f for f in os.listdir(out_dir) if f.endswith(".box")
    )
    assert boxes == ["mic0.box", "mic1.box"]


def test_pick_cli_trace_dir_and_device_time(params, tmp_path, rng):
    """ISSUE 7 satellite: the observability flags are wired into
    `pick`, not just `consensus` — a traced, device-timed pick run
    leaves the trace dir, device-split span fields, and the
    trace_dir breadcrumb next to its outputs."""
    import json

    from repic_tpu.main import main as cli_main
    from repic_tpu.telemetry import events as tlm_events
    from repic_tpu.telemetry import probes
    from repic_tpu.utils import mrc

    mrc_dir = tmp_path / "mrcs"
    out_dir = tmp_path / "out"
    trace_dir = tmp_path / "trace"
    mrc_dir.mkdir()
    mrc.write_mrc(
        str(mrc_dir / "mic0.mrc"),
        rng.normal(size=(400, 400)).astype(np.float32),
    )
    ckpt = str(tmp_path / "model.rptpu")
    save_checkpoint(
        ckpt, params, {"particle_size": 120, "patch_norm": "reference"}
    )
    try:
        cli_main(
            [
                "pick", ckpt, str(mrc_dir), str(out_dir),
                "--trace-dir", str(trace_dir), "--device-time",
            ]
        )
    finally:
        probes.set_device_time(False)  # process-wide: restore
    assert trace_dir.exists()
    records = tlm_events.read_events(str(out_dir))
    span = next(
        r for r in records
        if r.get("ev") == "span" and r["name"] == "pick_micrograph"
    )
    assert "device_tail_s" in span and "host_s" in span
    breadcrumb = next(
        r for r in records
        if r.get("ev") == "event" and r.get("name") == "trace_dir"
    )
    assert json.loads(json.dumps(breadcrumb))["path"] == str(
        trace_dir.resolve()
    )
