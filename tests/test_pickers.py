"""External picker adapter tests (no conda required).

VERDICT round 1 weak 5: the argv builders in pipeline/pickers.py had
zero coverage — a typo would ship silently.  These tests pin each
command line against the reference Bash adapters
(run_cryolo.sh:22-36, fit_cryolo.sh:26-44, run_deep.sh:22-28,
fit_deep.sh:44-52, run_topaz.sh:19-36, fit_topaz.sh:33-39,
preprocess_topaz.sh) and exercise the conda-run wrapper against a
stub ``conda`` executable on PATH.
"""

import json
import os
import stat

import pytest

from repic_tpu.pipeline.pickers import (
    CryoloPicker,
    DeepPickerExternal,
    PickerError,
    TopazPicker,
)


@pytest.fixture
def cryolo():
    return CryoloPicker(
        name="cryolo", conda_env="cryolo", particle_size=180,
        model_path="/models/gmodel.h5",
    )


@pytest.fixture
def deep():
    return DeepPickerExternal(
        name="deep", conda_env="deep", particle_size=180,
        deep_dir="/opt/DeepPicker", model_path="/models/demo_type3",
        batch_size=512,
    )


@pytest.fixture
def topaz():
    return TopazPicker(
        name="topaz", conda_env="topaz", particle_size=180,
        scale=4, radius=8,
    )


def test_cryolo_predict_cmd(cryolo):
    # run_cryolo.sh:30-36: -c config -w model -i mrc -o out -t 0.0
    # --write_empty (the -g GPU pin is deliberately omitted)
    cmd = cryolo.predict_cmd("/mrc", "/out", "/work/config.json")
    assert cmd[0] == "cryolo_predict.py"
    flags = dict(zip(cmd[1::2], cmd[2::2]))
    assert flags["-c"] == "/work/config.json"
    assert flags["-w"] == "/models/gmodel.h5"
    assert flags["-i"] == "/mrc"
    assert flags["-o"] == "/out"
    assert flags["-t"] == "0.0"
    assert cmd[-1] == "--write_empty"


def test_cryolo_fit_cmd(cryolo):
    # fit_cryolo.sh:40-44: -w 5 (warm restart) -e 32 (early stop)
    # --seed 1
    cmd = cryolo.fit_cmd("/work/config.json")
    assert cmd[0] == "cryolo_train.py"
    flags = dict(zip(cmd[1::2], cmd[2::2]))
    assert flags["-c"] == "/work/config.json"
    assert flags["-w"] == "5"
    assert flags["-e"] == "32"
    assert flags["--seed"] == "1"


def test_cryolo_config_json(cryolo, tmp_path):
    # run_cryolo.sh:22-27 — LOWPASS filter, cutoff 0.1; fit_cryolo.sh
    # adds train/valid folders, batch_size 2, saved_weights_name
    path = str(tmp_path / "config.json")
    cryolo._write_config(path, str(tmp_path))
    cfg = json.load(open(path))
    assert cfg["model"]["anchors"] == [180, 180]
    assert cfg["model"]["filter"][0] == 0.1
    assert "train" not in cfg

    cryolo._write_config(
        path, str(tmp_path),
        train=("/tmrc", "/tbox", "/vmrc", "/vbox", "/out/w.h5"),
    )
    cfg = json.load(open(path))
    assert cfg["train"]["train_image_folder"] == "/tmrc"
    assert cfg["train"]["train_annot_folder"] == "/tbox"
    assert cfg["train"]["batch_size"] == 2  # fit_cryolo.sh:38
    assert cfg["train"]["saved_weights_name"] == "/out/w.h5"
    assert cfg["valid"]["valid_image_folder"] == "/vmrc"
    assert cfg["valid"]["valid_annot_folder"] == "/vbox"


def test_deep_predict_cmd(deep):
    # run_deep.sh:22-28
    cmd = deep.predict_cmd("/mrc", "/out/STAR")
    assert cmd[:2] == ["python", "/opt/DeepPicker/autoPick.py"]
    flags = dict(zip(cmd[2::2], cmd[3::2]))
    assert flags["--inputDir"] == "/mrc"
    assert flags["--pre_trained_model"] == "/models/demo_type3"
    assert flags["--particle_size"] == "180"
    assert flags["--outputDir"] == "/out/STAR"
    assert flags["--threshold"] == "0.0"


def test_deep_fit_cmd(deep):
    # fit_deep.sh:44-52: --train_type 1, --model_retrain from the
    # previous model, explicit validation dir (REPIC patch), batch size
    cmd = deep.fit_cmd("/train", "/val", "/out/model")
    assert cmd[:2] == ["python", "/opt/DeepPicker/train.py"]
    assert "--model_retrain" in cmd
    rest = [c for c in cmd[2:] if c != "--model_retrain"]
    flags = dict(zip(rest[0::2], rest[1::2]))
    assert flags["--train_type"] == "1"
    assert flags["--train_inputDir"] == "/train"
    assert flags["--validation_inputDir"] == "/val"
    assert flags["--particle_size"] == "180"
    assert flags["--model_load_file"] == "/models/demo_type3"
    assert flags["--model_save_file"] == "/out/model"
    assert flags["--batch_size"] == "512"


def test_topaz_preprocess_cmd(topaz, tmp_path):
    # preprocess_topaz.sh — downsample by TOPAZ_SCALE into down_dir
    for f in ("b.mrc", "a.mrc", "notes.txt"):
        (tmp_path / f).write_text("")
    cmd = topaz.preprocess_cmd(str(tmp_path), "/down")
    assert cmd[:2] == ["topaz", "preprocess"]
    flags = dict(zip(cmd[2:6:2], cmd[3:7:2]))
    assert flags["-s"] == "4"
    assert flags["-o"] == "/down"
    # mrc files only, sorted
    assert cmd[6:] == [
        str(tmp_path / "a.mrc"), str(tmp_path / "b.mrc")
    ]


def test_topaz_predict_cmd(topaz, tmp_path):
    # run_topaz.sh:19-36 — general model when no -m, fitted model
    # otherwise (coordinates are upscaled host-side instead of -x)
    (tmp_path / "m1.mrc").write_text("")
    cmd = topaz.predict_cmd(str(tmp_path), "/out/extracted.txt")
    assert cmd[:2] == ["topaz", "extract"]
    assert "-m" not in cmd  # general model path (run_topaz.sh:24-28)
    flags = dict(zip(cmd[2::2], cmd[3::2]))
    assert flags["-r"] == "8"
    assert flags["-o"] == "/out/extracted.txt"

    topaz.model_path = "/models/topaz.sav"
    cmd = topaz.predict_cmd(str(tmp_path), "/out/extracted.txt")
    flags = dict(zip(cmd[2::2], cmd[3::2]))
    assert flags["-m"] == "/models/topaz.sav"


def test_topaz_fit_cmd(topaz):
    # fit_topaz.sh:33-39 — expected particles x1.25 and measured
    # minibatch balance
    cmd = topaz.fit_cmd("/down", "/targets.txt", "/out/model", 400)
    assert cmd[:2] == ["topaz", "train"]
    flags = dict(zip(cmd[2::2], cmd[3::2]))
    assert flags["--train-images"] == "/down"
    assert flags["--train-targets"] == "/targets.txt"
    assert flags["--num-particles"] == "500"  # 400 * 1.25
    assert flags["--save-prefix"] == "/out/model"
    assert "--minibatch-balance" not in cmd

    topaz.balance = 0.0625
    cmd = topaz.fit_cmd("/down", "/targets.txt", "/out/model", 400)
    flags = dict(zip(cmd[2::2], cmd[3::2]))
    assert flags["--minibatch-balance"] == "0.062500"


# --- conda-run wrapper against a stub conda ------------------------


def _stub_conda(tmp_path, rc=0):
    """Executable `conda` stub that records its argv and exits rc."""
    record = tmp_path / "conda_argv.txt"
    stub = tmp_path / "conda"
    stub.write_text(
        "#!/bin/sh\n"
        f'echo "$@" >> {record}\n'
        "echo stub-stdout\n"
        f"exit {rc}\n"
    )
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    return record


def test_run_wraps_with_conda_run(cryolo, tmp_path, monkeypatch):
    record = _stub_conda(tmp_path)
    monkeypatch.setenv(
        "PATH", f"{tmp_path}{os.pathsep}{os.environ['PATH']}"
    )
    log = tmp_path / "run.log"
    cryolo._run(["echo", "hello"], log_path=str(log))
    argv = record.read_text().strip()
    # the Bash adapters' `conda activate env && cmd` becomes
    # `conda run -n env cmd`
    assert argv == "run -n cryolo echo hello"
    assert "stub-stdout" in log.read_text()


def test_run_raises_picker_error_on_failure(cryolo, tmp_path, monkeypatch):
    _stub_conda(tmp_path, rc=3)
    monkeypatch.setenv(
        "PATH", f"{tmp_path}{os.pathsep}{os.environ['PATH']}"
    )
    with pytest.raises(PickerError, match="command failed"):
        cryolo._run(["boom"])


def test_run_raises_without_conda(cryolo, tmp_path, monkeypatch):
    monkeypatch.setenv("PATH", str(tmp_path))  # empty PATH dir
    with pytest.raises(PickerError, match="conda not available"):
        cryolo._run(["anything"])


def test_extra_env_passed_through(cryolo, tmp_path, monkeypatch):
    record = _stub_conda(tmp_path)
    env_record = tmp_path / "env.txt"
    stub = tmp_path / "conda"
    stub.write_text(
        "#!/bin/sh\n"
        f'echo "$@" >> {record}\n'
        f'echo "$REPIC_TEST_VAR" > {env_record}\n'
    )
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv(
        "PATH", f"{tmp_path}{os.pathsep}{os.environ['PATH']}"
    )
    cryolo.extra_env = {"REPIC_TEST_VAR": "42"}
    cryolo._run(["x"])
    assert env_record.read_text().strip() == "42"
