"""Stub-binary integration tests for the external picker adapters.

Round-3 verdict item 5: the adapters' argv construction was pinned
against the reference Bash contracts
(reference: repic/iterative_particle_picking/run_cryolo.sh:22-36,
run_deep.sh:22-28, run_topaz.sh:19-48), but ``ExternalPicker._run``
and the CBOX/STAR/TSV->BOX post-processing had never been driven
end-to-end.  Here fake ``conda`` / ``cryolo_predict.py`` / ``topaz``
/ DeepPicker executables on PATH emit realistic output files, and the
adapters run through the REAL subprocess + conversion machinery.

The fake ``conda`` honours the exact invocation shape the adapters
produce (``conda run -n <env> cmd...``, mirroring the reference's
``conda activate && cmd`` — run_cryolo.sh:19) and execs the command
with the stub bin dir still on PATH.
"""

import json
import os
import stat
import subprocess
import sys

import numpy as np
import pytest

from repic_tpu.pipeline.pickers import (
    CryoloPicker,
    DeepPickerExternal,
    PickerError,
    TopazPicker,
)
from repic_tpu.utils.box_io import read_box

BOX = 40  # particle size used throughout


def _script(path, body, interpreter="/bin/bash"):
    with open(path, "wt") as f:
        f.write(f"#!{interpreter}\n" + body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC | stat.S_IXGRP)
    return str(path)


# conda shim: validate the `run -n <env>` prefix, then exec the rest.
_CONDA = """
if [ "$1" != run ] || [ "$2" != -n ]; then
  echo "unexpected conda argv: $*" >&2
  exit 9
fi
echo "$3" > "${STUB_LOG_DIR:-/tmp}/conda_env_used"
shift 3
exec "$@"
"""

# crYOLO predict stub: per input micrograph, write a CBOX file under
# <out>/CBOX with the STAR-style header crYOLO emits; honours
# --write_empty by emitting a data-less CBOX for `empty_mic`.
_CRYOLO_PREDICT = """
import argparse, glob, os, sys
p = argparse.ArgumentParser()
p.add_argument("-c"); p.add_argument("-w"); p.add_argument("-i")
p.add_argument("-o"); p.add_argument("-t");
p.add_argument("--write_empty", action="store_true")
a = p.parse_args()
assert a.t == "0.0", f"threshold {a.t} != 0.0 (run_cryolo.sh:31)"
import json
cfg = json.load(open(a.c))
assert cfg["model"]["anchors"] == [40, 40], cfg
cbox_dir = os.path.join(a.o, "CBOX")
os.makedirs(cbox_dir, exist_ok=True)
HEADER = (
    "data_cryolo_\\n\\nloop_\\n_CoordinateX #1\\n_CoordinateY #2\\n"
    "_CoordinateZ #3\\n_Width #4\\n_Height #5\\n_Depth #6\\n"
    "_EstWidth #7\\n_EstHeight #8\\n_Confidence #9\\n_NumBoxes #10\\n"
)
for mrc in sorted(glob.glob(os.path.join(a.i, "*.mrc"))):
    stem = os.path.splitext(os.path.basename(mrc))[0]
    with open(os.path.join(cbox_dir, stem + ".cbox"), "wt") as f:
        f.write(HEADER)
        if stem == "empty_mic":
            if not a.write_empty:
                os.unlink(f.name)
            continue
        f.write("10.0 20.0 0 40 40 0 38.0 39.0 0.90 1\\n")
        f.write("30.0 44.0 0 40 40 0 38.0 39.0 0.80 1\\n")
"""

_CRYOLO_TRAIN = """
import argparse, json, os
p = argparse.ArgumentParser()
p.add_argument("-c"); p.add_argument("-w"); p.add_argument("-e")
p.add_argument("--seed")
a = p.parse_args()
assert a.e == "32" and a.seed == "1", (a.e, a.seed)
cfg = json.load(open(a.c))
assert os.path.isdir(cfg["train"]["train_image_folder"])
assert os.path.isdir(cfg["valid"]["valid_annot_folder"])
with open(cfg["train"]["saved_weights_name"], "wt") as f:
    f.write("fake-h5-weights")
"""

# topaz stub: `preprocess` copies micrographs into the downsample dir,
# `extract` writes the single TSV extraction table on the downsampled
# grid, `train` records its arguments and writes the model file.
_TOPAZ = """
import argparse, os, shutil, sys
sub = sys.argv[1]
if sub == "preprocess":
    p = argparse.ArgumentParser()
    p.add_argument("-s"); p.add_argument("-o"); p.add_argument("files", nargs="+")
    a = p.parse_args(sys.argv[2:])
    os.makedirs(a.o, exist_ok=True)
    for f in a.files:
        shutil.copy(f, os.path.join(a.o, os.path.basename(f)))
elif sub == "extract":
    p = argparse.ArgumentParser()
    p.add_argument("-r"); p.add_argument("-m", default=None)
    p.add_argument("-o"); p.add_argument("files", nargs="+")
    a = p.parse_args(sys.argv[2:])
    assert a.r == "8", a.r
    with open(a.o, "wt") as f:
        f.write("image_name\\tx_coord\\ty_coord\\tscore\\n")
        for mrc in a.files:
            stem = os.path.splitext(os.path.basename(mrc))[0]
            if stem == "empty_mic":
                continue
            f.write(f"{stem}\\t25\\t35\\t0.75\\n")
            f.write(f"{stem}\\t50\\t60\\t0.25\\n")
elif sub == "train":
    p = argparse.ArgumentParser()
    p.add_argument("--train-images"); p.add_argument("--train-targets")
    p.add_argument("--num-particles"); p.add_argument("--save-prefix")
    p.add_argument("--minibatch-balance", default=None)
    a = p.parse_args(sys.argv[2:])
    assert os.path.exists(a.train_targets)
    with open(a.save_prefix, "wt") as f:
        f.write(f"num_particles={a.num_particles}\\n")
        f.write(f"balance={a.minibatch_balance}\\n")
else:
    sys.exit(f"unknown subcommand {sub}")
"""

# DeepPicker stubs live in a fake checkout dir (invoked as
# `python <deep_dir>/autoPick.py`, run_deep.sh:22-28).
_AUTOPICK = """
import argparse, glob, os
p = argparse.ArgumentParser()
p.add_argument("--inputDir"); p.add_argument("--pre_trained_model")
p.add_argument("--particle_size"); p.add_argument("--outputDir")
p.add_argument("--threshold")
a = p.parse_args()
assert a.threshold == "0.0", a.threshold
os.makedirs(a.outputDir, exist_ok=True)
for mrc in sorted(glob.glob(os.path.join(a.inputDir, "*.mrc"))):
    stem = os.path.splitext(os.path.basename(mrc))[0]
    if stem == "empty_mic":
        continue
    with open(os.path.join(a.outputDir, stem + ".star"), "wt") as f:
        f.write("data_\\n\\nloop_\\n_rlnCoordinateX #1\\n"
                "_rlnCoordinateY #2\\n_rlnAutopickFigureOfMerit #3\\n")
        f.write("100.0\\t120.0\\t0.95\\n")
        f.write("200.0\\t220.0\\t0.65\\n")
"""

_DEEP_TRAIN = """
import argparse, os
p = argparse.ArgumentParser()
p.add_argument("--train_type"); p.add_argument("--train_inputDir")
p.add_argument("--validation_inputDir"); p.add_argument("--particle_size")
p.add_argument("--model_retrain", action="store_true")
p.add_argument("--model_load_file"); p.add_argument("--model_save_file")
p.add_argument("--batch_size")
a = p.parse_args()
assert a.train_type == "1" and a.model_retrain
assert any(f.endswith(".star") for f in os.listdir(a.train_inputDir))
assert any(f.endswith(".mrc") for f in os.listdir(a.train_inputDir))
with open(a.model_save_file, "wt") as f:
    f.write("fake-deep-model")
"""


@pytest.fixture
def stub_env(tmp_path, monkeypatch):
    """Fake conda + picker binaries on PATH, plus input micrographs."""
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    _script(bin_dir / "conda", _CONDA)
    _script(bin_dir / "cryolo_predict.py", _CRYOLO_PREDICT,
            interpreter=sys.executable)
    _script(bin_dir / "cryolo_train.py", _CRYOLO_TRAIN,
            interpreter=sys.executable)
    _script(bin_dir / "topaz", _TOPAZ, interpreter=sys.executable)
    monkeypatch.setenv(
        "PATH", f"{bin_dir}{os.pathsep}" + os.environ.get("PATH", "")
    )
    monkeypatch.setenv("STUB_LOG_DIR", str(tmp_path))

    mrc_dir = tmp_path / "mrc"
    mrc_dir.mkdir()
    for stem in ("mic_a", "mic_b", "empty_mic"):
        (mrc_dir / f"{stem}.mrc").write_bytes(b"\x00" * 64)

    deep_dir = tmp_path / "DeepPicker"
    deep_dir.mkdir()
    _script(deep_dir / "autoPick.py", _AUTOPICK,
            interpreter=sys.executable)
    _script(deep_dir / "train.py", _DEEP_TRAIN,
            interpreter=sys.executable)
    return tmp_path


def _box_dir(tmp_path, name, coords):
    """A labels dir with one BOX file of corner coords."""
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    with open(d / "mic_a.box", "wt") as f:
        for x, y in coords:
            f.write(f"{x}\t{y}\t{BOX}\t{BOX}\t1.0\n")
    return str(d)


def test_cryolo_predict_end_to_end(stub_env):
    p = CryoloPicker(
        name="cryolo", conda_env="cryolo_env", particle_size=BOX,
        model_path="weights.h5",
    )
    out = stub_env / "picks"
    total = p.predict(str(stub_env / "mrc"), str(out))
    assert total == 4  # 2 particles x 2 non-empty micrographs
    # conda wrapper used the configured env (run_cryolo.sh:19)
    assert (stub_env / "conda_env_used").read_text().strip() == "cryolo_env"
    # CBOX coordinates pass through unshifted (coords.py Format:
    # cbox is centered=None -> no geometry shift, reference parity)
    bs = read_box(str(out / "mic_a.box"))
    got = sorted(map(tuple, np.c_[bs.xy, bs.conf].tolist()))
    assert got == [(10.0, 20.0, pytest.approx(0.9)),
                   (30.0, 44.0, pytest.approx(0.8))]
    assert np.all(bs.wh == BOX)
    # --write_empty micrograph backfilled as an empty placeholder
    assert read_box(str(out / "empty_mic.box")).n == 0
    assert (out / "cryolo_predict.log").exists()


def test_cryolo_fit_end_to_end(stub_env, tmp_path):
    p = CryoloPicker(
        name="cryolo", conda_env="cryolo_env", particle_size=BOX,
    )
    train_box = _box_dir(tmp_path, "train_box", [(80, 80)])
    val_box = _box_dir(tmp_path, "val_box", [(80, 80)])
    model_out = str(tmp_path / "work" / "cryolo_model.h5")
    os.makedirs(os.path.dirname(model_out), exist_ok=True)
    p.fit(str(stub_env / "mrc"), train_box, str(stub_env / "mrc"),
          val_box, model_out)
    assert open(model_out).read() == "fake-h5-weights"
    assert p.model_path == model_out
    # the config the stub validated is the one _write_config produced
    cfg = json.load(open(tmp_path / "work" / "cryolo_train_config.json"))
    assert cfg["train"]["batch_size"] == 2  # fit_cryolo.sh:38


def test_topaz_predict_end_to_end(stub_env):
    p = TopazPicker(
        name="topaz", conda_env="topaz_env", particle_size=BOX,
        scale=4, radius=8,
    )
    out = stub_env / "picks"
    total = p.predict(str(stub_env / "mrc"), str(out))
    assert total == 4
    # extraction coords are on the downsampled grid: upscale by
    # scale then shift center->corner (run_topaz.sh:36-48):
    # (25,35) * 4 - 40/2 = (80, 120)
    bs = read_box(str(out / "mic_a.box"))
    got = sorted(map(tuple, np.c_[bs.xy, bs.conf].tolist()))
    assert got == [(80.0, 120.0, pytest.approx(0.75)),
                   (180.0, 220.0, pytest.approx(0.25))]
    # micrograph absent from the extraction table -> empty placeholder
    assert read_box(str(out / "empty_mic.box")).n == 0
    assert (out / "topaz_preprocess.log").exists()
    assert (out / "topaz_extract.log").exists()


def test_topaz_fit_end_to_end(stub_env, tmp_path):
    p = TopazPicker(
        name="topaz", conda_env="topaz_env", particle_size=BOX,
        scale=4, radius=8, balance=0.125,
    )
    # corner (80, 80) -> center (100, 100) -> downscaled (25, 25)
    train_box = _box_dir(tmp_path, "train_box", [(80, 80), (120, 160)])
    model_out = str(tmp_path / "work" / "topaz_model.sav")
    os.makedirs(os.path.dirname(model_out), exist_ok=True)
    p.fit(str(stub_env / "mrc"), train_box, str(stub_env / "mrc"),
          _box_dir(tmp_path, "val_box", [(80, 80)]), model_out)
    saved = open(model_out).read()
    # 2 particles / 1 micrograph -> expected 2, x1.25 = 2 (int)
    assert "num_particles=2" in saved  # fit_topaz.sh:33-39 x1.25
    assert "balance=0.125000" in saved
    targets = open(tmp_path / "work" / "topaz_targets.txt").read()
    assert "mic_a\t25\t25" in targets
    assert "mic_a\t35\t45" in targets  # (120+20)/4, (160+20)/4
    assert p.model_path == model_out


def test_deeppicker_predict_end_to_end(stub_env):
    p = DeepPickerExternal(
        name="deep", conda_env="deep_env", particle_size=BOX,
        deep_dir=str(stub_env / "DeepPicker"), model_path="model.ckpt",
    )
    out = stub_env / "picks"
    total = p.predict(str(stub_env / "mrc"), str(out))
    assert total == 4
    # STAR is a centered format: center->corner shift by box/2
    # (coord_converter.py:366): (100,120) - 20 = (80, 100)
    bs = read_box(str(out / "mic_a.box"))
    got = sorted(map(tuple, np.c_[bs.xy, bs.conf].tolist()))
    assert got == [(80.0, 100.0, pytest.approx(0.95)),
                   (180.0, 200.0, pytest.approx(0.65))]
    assert read_box(str(out / "empty_mic.box")).n == 0


def test_deeppicker_fit_end_to_end(stub_env, tmp_path):
    p = DeepPickerExternal(
        name="deep", conda_env="deep_env", particle_size=BOX,
        deep_dir=str(stub_env / "DeepPicker"), model_path="old.ckpt",
    )
    train_box = _box_dir(tmp_path, "train_box", [(80, 80)])
    val_box = _box_dir(tmp_path, "val_box", [(80, 80)])
    model_out = str(tmp_path / "work" / "deep_model.ckpt")
    os.makedirs(os.path.dirname(model_out), exist_ok=True)
    p.fit(str(stub_env / "mrc"), train_box, str(stub_env / "mrc"),
          val_box, model_out)
    assert open(model_out).read() == "fake-deep-model"
    assert p.model_path == model_out
    # staged layout: STAR labels + symlinked micrographs
    staged = tmp_path / "work" / "deep_train"
    assert (staged / "mic_a.star").exists()
    assert (staged / "mic_a.mrc").is_symlink()


def test_failing_binary_raises_with_log(stub_env, monkeypatch):
    """A nonzero exit surfaces as PickerError AND leaves the log."""
    bad = stub_env / "bin" / "cryolo_predict.py"
    _script(bad, "import sys; sys.stderr.write('boom: no GPU')\n"
                 "sys.exit(3)\n", interpreter=sys.executable)
    p = CryoloPicker(
        name="cryolo", conda_env="cryolo_env", particle_size=BOX,
        model_path="weights.h5",
    )
    out = stub_env / "picks"
    with pytest.raises(PickerError, match="boom: no GPU"):
        p.predict(str(stub_env / "mrc"), str(out))
    assert "boom" in (out / "cryolo_predict.log").read_text()


def test_header_only_tsv_converts_to_empty(tmp_path):
    """A topaz-style extraction table with a header but zero data
    rows must convert to an empty frame, not sys.exit (regression:
    the header-only CBOX fix initially dropped column structure,
    which killed the tsv path's geometry shift)."""
    from repic_tpu.utils import coords as coords_mod

    f = tmp_path / "ex.tsv"
    f.write_text("image_name\tx_coord\ty_coord\tscore\n")
    dfs = coords_mod.convert(
        [str(f)], "tsv", "box", boxsize=BOX, quiet=True
    )
    (df,) = dfs.values()
    assert len(df) == 0
