"""``repic-tpu report``: the journal + events + metrics join.

The acceptance scenario of the telemetry subsystem
(docs/observability.md): a journaled fixture consensus run must
report per-stage latency percentiles, ladder-rung/retry/quarantine
tallies, and recompile + transfer counters — and degrade to
journal-only tallies when telemetry was disabled for the run.
"""

import json
import os

import numpy as np
import pytest

from repic_tpu.main import main as cli_main
from repic_tpu.pipeline.consensus import run_consensus_dir
from repic_tpu.telemetry import metrics as tlm_metrics
from repic_tpu.telemetry.report import build_report, format_report


def _make_dir(tmp_path, m=6, k=3, n=30, seed=0):
    rng = np.random.default_rng(seed)
    d = tmp_path / "picks"
    for p in range(k):
        (d / f"picker{p}").mkdir(parents=True)
    for i in range(m):
        base = rng.uniform(50, 950, size=(n, 2))
        for p in range(k):
            jit = rng.normal(0, 10, size=base.shape)
            conf = rng.uniform(0.1, 1.0, size=n)
            with open(d / f"picker{p}" / f"mic{i}.box", "wt") as f:
                for (x, y), c in zip(base + jit, conf):
                    f.write(f"{x:.2f}\t{y:.2f}\t64\t64\t{c:.4f}\n")
    return str(d)


def _corrupt(data, name="mic2", picker="picker0"):
    path = os.path.join(data, picker, name + ".box")
    with open(path, "wt") as f:
        f.write("x y w h conf\nthis is not a number at all\n")


@pytest.fixture
def journaled_run(tmp_path, monkeypatch):
    """A lenient chunked exact-solver run with one quarantined
    micrograph — journal, events, and metrics all populated."""
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "2")
    # n=70 buckets to a particle capacity no other test uses, so the
    # run really compiles (recompiles >= 1) regardless of suite order
    data = _make_dir(tmp_path, n=70)
    _corrupt(data, "mic2")
    out = str(tmp_path / "out")
    stats = run_consensus_dir(
        data, out, 64, use_mesh=False, solver="exact"
    )
    return out, stats


def test_report_joins_all_artifacts(journaled_run):
    out, stats = journaled_run
    assert os.path.exists(os.path.join(out, "_events.jsonl"))
    assert os.path.exists(os.path.join(out, "_metrics.json"))
    assert os.path.exists(os.path.join(out, "_metrics.prom"))

    report = build_report(out)
    # outcome tallies from the journal
    by_status = report["micrographs"]["by_status"]
    assert by_status["quarantined"] == 1
    assert by_status.get("ok", 0) + by_status.get("degraded", 0) == 5
    assert report["micrographs"]["total"] == 6
    # the exact host-solver rung recorded per micrograph
    assert sum(report["solver_rungs"].values()) == 5
    assert set(report["solver_rungs"]) <= {"exact", "lp", "greedy"}
    # ladder tallies present even when zero
    assert report["ladder"]["chunk_halvings"] == 0
    # stage latency percentiles over the chunked spans (3 chunks)
    chunk = report["stages"]["consensus_chunk"]
    assert chunk["count"] == 3
    assert 0 < chunk["p50_s"] <= chunk["p95_s"] <= chunk["max_s"]
    for stage in ("load", "write", "host_solve"):
        assert report["stages"][stage]["count"] >= 1
    # device counters: CPU still compiles XLA programs, and the
    # packed-fetch sites record their transfers
    assert report["device"]["recompiles"] >= 1
    assert report["device"]["transfer_bytes"] > 0
    assert report["device"]["transfer_fetches"] >= 1
    # legacy TSV joined too
    assert set(report["runtime_tsv"]) >= {"load", "compute", "write"}


def test_format_report_surfaces_the_acceptance_fields(journaled_run):
    out, _ = journaled_run
    text = format_report(build_report(out))
    assert "p50" in text and "p95" in text
    assert "quarantined=1" in text
    assert "solver rungs:" in text
    assert "recompiles=" in text
    assert "transfers=" in text
    assert "chunk_retries=" in text


def test_report_cli_text_and_json(journaled_run, capsys):
    out, _ = journaled_run
    cli_main(["report", out])
    text = capsys.readouterr().out
    assert "stage latencies" in text
    assert "micrographs: 6" in text

    cli_main(["report", out, "--json"])
    data = json.loads(capsys.readouterr().out)
    assert data["micrographs"]["by_status"]["quarantined"] == 1
    assert data["stages"]["consensus_chunk"]["count"] == 3
    assert data["device"]["transfer_bytes"] > 0


def test_report_degrades_without_telemetry(tmp_path, monkeypatch):
    """Telemetry disabled: the run leaves only the journal, no event
    or metric files appear, and the report still tallies outcomes."""
    data = _make_dir(tmp_path, m=3)
    out = str(tmp_path / "out")
    monkeypatch.setattr(tlm_metrics.REGISTRY, "_enabled", False)
    run_consensus_dir(data, out, 64, use_mesh=False)
    monkeypatch.setattr(tlm_metrics.REGISTRY, "_enabled", True)

    assert not os.path.exists(os.path.join(out, "_events.jsonl"))
    assert not os.path.exists(os.path.join(out, "_metrics.json"))
    report = build_report(out)
    assert report["micrographs"]["by_status"] == {"ok": 3}
    assert report["stages"] == {}
    assert "no event stream" in format_report(report)


def test_report_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        build_report(str(tmp_path / "nope"))


def test_report_tolerates_torn_journal_line(journaled_run):
    """A crash mid-append tears the last journal line; the post-
    mortem report must summarize the run anyway."""
    out, _ = journaled_run
    with open(os.path.join(out, "_journal.jsonl"), "at") as f:
        f.write('{"name": "mic9", "status": "o')
    report = build_report(out)
    assert report["micrographs"]["total"] == 6  # torn line skipped


def test_strict_raise_still_finishes_telemetry(tmp_path):
    """finish_run runs from the finally: a --strict failure restores
    the previous event log and still writes the metric sinks."""
    from repic_tpu.telemetry import events as tlm_events
    from repic_tpu.utils.box_io import BoxParseError

    data = _make_dir(tmp_path, m=3)
    _corrupt(data, "mic1")
    out = str(tmp_path / "out")
    with pytest.raises(BoxParseError):
        run_consensus_dir(data, out, 64, use_mesh=False, strict=True)
    assert tlm_events.current_log() is None  # no leaked global log
    assert os.path.exists(os.path.join(out, "_metrics.json"))
    # a follow-up lenient run in the same process must write its own
    # log, not append to the failed run's
    size_failed = os.path.getsize(os.path.join(out, "_events.jsonl"))
    run_consensus_dir(data, out + "2", 64, use_mesh=False)
    assert (
        os.path.getsize(os.path.join(out, "_events.jsonl"))
        == size_failed
    )
    assert len(
        {r["run"] for r in tlm_events.read_events(out + "2")}
    ) == 1


def test_metrics_snapshot_is_per_run(tmp_path):
    """Two runs in one process: each run's _metrics.json reports its
    OWN counters/probe totals, not the process-cumulative ones."""
    from repic_tpu.telemetry import sinks as tlm_sinks

    # unique particle count -> fresh padded shape -> run 1 really
    # compiles (same-shape earlier tests would otherwise hit the
    # in-process jit cache and legitimately report 0 recompiles)
    data = _make_dir(tmp_path, m=3, n=37)
    out1 = str(tmp_path / "r1")
    out2 = str(tmp_path / "r2")
    run_consensus_dir(data, out1, 64, use_mesh=False)
    run_consensus_dir(data, out2, 64, use_mesh=False)

    def micrographs_total(out):
        m = tlm_sinks.read_metrics_json(out)
        samples = m["repic_consensus_micrographs_total"]["samples"]
        return sum(s["value"] for s in samples)

    assert micrographs_total(out1) == 3
    assert micrographs_total(out2) == 3  # not 6: per-run delta

    # the identical second run reuses every compiled program, so its
    # per-run recompile delta must be below the first run's total
    r1 = build_report(out1)
    r2 = build_report(out2)
    assert r1["device"]["recompiles"] >= 1
    assert r2["device"]["recompiles"] < r1["device"]["recompiles"]


def test_events_stream_has_run_id_and_chunk_spans(journaled_run):
    from repic_tpu.telemetry import events as tlm_events

    out, _ = journaled_run
    records = tlm_events.read_events(out)
    assert records, "run should have produced event records"
    run_ids = {r.get("run") for r in records}
    assert len(run_ids) == 1
    spans = [r for r in records if r.get("ev") == "span"]
    names = {s["name"] for s in spans}
    assert {"consensus_chunk", "load", "write"} <= names
    # chunk spans carry their micrograph count (5 loaded at chunk
    # size 2 -> chunks of 2, 2, 1)
    chunk_spans = [s for s in spans if s["name"] == "consensus_chunk"]
    assert sorted(s["micrographs"] for s in chunk_spans) == [1, 2, 2]


def test_report_json_carries_schema_version(journaled_run, capsys):
    """Satellite: the --json output pins its field contract
    (docs/observability.md "Report JSON contract").  v3 added the
    per-request ``requests`` section (trace-artifact join)."""
    out_dir, _ = journaled_run
    report = build_report(out_dir)
    assert report["schema_version"] == 3
    cli_main(["report", out_dir, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema_version"] == 3


def test_report_merges_per_host_metrics_and_events(tmp_path):
    """Cluster artifacts: per-host _metrics.<host>.json sum into the
    device section and break out per host in the cluster section;
    per-host event logs merge into one stage table."""
    from repic_tpu.telemetry import sinks as tlm_sinks

    out = tmp_path / "run"
    out.mkdir()
    # two hosts' journals (cluster mode markers)
    with open(out / "_journal.h1.jsonl", "wt") as f:
        f.write(json.dumps(
            {"name": "mic0", "status": "ok", "ts": 1.0, "host": "h1"}
        ) + "\n")
    with open(out / "_journal.h2.jsonl", "wt") as f:
        f.write(json.dumps(
            {"name": "mic1", "status": "ok", "ts": 2.0, "host": "h2"}
        ) + "\n")
    # two hosts' metric snapshots with probe gauges
    def _snap(host, bytes_, recompiles):
        data = {
            "repic_transfer_bytes_total": {
                "kind": "gauge", "help": "",
                "samples": [{"labels": {}, "value": bytes_}],
            },
            "repic_recompiles_total": {
                "kind": "gauge", "help": "",
                "samples": [{"labels": {}, "value": recompiles}],
            },
        }
        tlm_sinks.write_metrics_json(
            str(out / tlm_sinks.host_metrics_json_name(host)),
            data=data,
        )
    _snap("h1", 1000, 2)
    _snap("h2", 500, 1)
    # two hosts' event logs
    with open(out / "_events.h1.jsonl", "wt") as f:
        f.write(json.dumps(
            {"ev": "span", "name": "consensus_chunk", "run": "r",
             "t": 1.0, "dur_s": 0.5}
        ) + "\n")
    with open(out / "_events.h2.jsonl", "wt") as f:
        f.write(json.dumps(
            {"ev": "span", "name": "consensus_chunk", "run": "r",
             "t": 2.0, "dur_s": 0.7}
        ) + "\n")

    report = build_report(str(out))
    assert report["device"]["transfer_bytes"] == 1500
    assert report["device"]["recompiles"] == 3
    assert report["stages"]["consensus_chunk"]["count"] == 2
    tele = report["cluster"]["telemetry"]
    assert tele == {
        "h1": {"recompiles": 2, "transfer_bytes": 1000},
        "h2": {"recompiles": 1, "transfer_bytes": 500},
    }
