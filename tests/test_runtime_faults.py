"""Fault-injection harness: spec parsing, determinism, read-site hooks.

The harness is the proof substrate for every rung of the runtime
ladder (docs/robustness.md), so its own semantics — count-based
determinism, substring keying, plan scoping — are pinned here first.
"""

import numpy as np
import pytest

from repic_tpu.runtime import faults
from repic_tpu.utils import box_io

pytestmark = pytest.mark.faults


def test_parse_spec_forms():
    f = faults.parse_spec("oom")
    assert (f.site, f.key, f.times) == ("oom", None, 1)
    f = faults.parse_spec("io:mic_002")
    assert (f.site, f.key, f.times) == ("io", "mic_002", 1)
    f = faults.parse_spec("io:mic_002:3")
    assert (f.site, f.key, f.times) == ("io", "mic_002", 3)
    f = faults.parse_spec("oom::inf")
    assert (f.site, f.key, f.times) == ("oom", None, None)
    f = faults.parse_spec("oom:mic:a:2")  # keys may contain ':'
    assert (f.site, f.key, f.times) == ("oom", "mic:a", 2)
    f = faults.parse_spec("io:*")
    assert f.key is None
    with pytest.raises(ValueError):
        faults.parse_spec(":key")


def test_count_based_determinism():
    with faults.fault_plan("oom:chunk:2"):
        assert faults.check("oom", "chunk:a") is True
        assert faults.check("oom", "other") is False  # key mismatch
        assert faults.check("oom", "chunk:b") is True
        assert faults.check("oom", "chunk:c") is False  # exhausted
        assert faults.fired_log() == (
            ("oom", "chunk:a"), ("oom", "chunk:b")
        )
    # plan scoping: inert outside the with-block
    assert faults.check("oom", "chunk:z") is False
    assert not faults.active()


def test_inject_raises_canonical_exceptions():
    with faults.fault_plan("oom", "io", "corrupt_box"):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            faults.inject("oom", "site")
        with pytest.raises(OSError, match="injected I/O"):
            faults.inject("io", "site")
        with pytest.raises(ValueError, match="corrupt BOX"):
            faults.inject("corrupt_box", "site")
        # all single-shot: second call is a no-op
        faults.inject("oom", "site")


def test_nested_plans_restore():
    with faults.fault_plan("oom::inf"):
        assert faults.check("oom", "x")
        with faults.fault_plan("io"):
            assert not faults.check("oom", "x")  # inner plan replaces
            assert faults.check("io", "y")
        assert faults.check("oom", "x")  # outer plan restored


def test_install_from_env():
    try:
        plan = faults.install_from_env(
            {"REPIC_TPU_FAULTS": "corrupt_box:mic_002, oom::1"}
        )
        assert [(f.site, f.key) for f in plan] == [
            ("corrupt_box", "mic_002"), ("oom", None)
        ]
        assert faults.install_from_env({}) == []  # unset: no-op
    finally:
        faults.clear()


def test_read_box_corrupt_injection_is_boxparseerror(tmp_path):
    p = tmp_path / "mic_002.box"
    p.write_text("10 20 64 64 0.5\n")
    with faults.fault_plan("corrupt_box:mic_002"):
        with pytest.raises(box_io.BoxParseError) as ei:
            box_io.read_box(str(p))
        assert ei.value.path == str(p)
        assert "mic_002" in str(ei.value)
        # single-shot: the retry parses fine
        bs = box_io.read_box(str(p))
        np.testing.assert_allclose(bs.xy, [[10, 20]])


def test_read_box_io_injection_is_oserror(tmp_path):
    p = tmp_path / "mic_007.box"
    p.write_text("10 20 64 64 0.5\n")
    with faults.fault_plan("io:mic_007"):
        with pytest.raises(OSError, match="injected I/O"):
            box_io.read_box(str(p))
        assert box_io.read_box(str(p)).n == 1
