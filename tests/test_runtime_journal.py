"""Run journal + manifest + atomic writes: the --resume substrate."""

import json
import os

import pytest

from repic_tpu.runtime.atomic import atomic_write
from repic_tpu.runtime.journal import RunJournal, error_info, read_journal

CFG = {"in_dir": "/data", "box_size": 64, "names": ["a", "b", "c"]}


def test_record_latest_and_summary(tmp_path):
    out = str(tmp_path / "run")
    with RunJournal.open(out, CFG) as j:
        j.record("a", "ok", wall_s=0.1, solver="greedy")
        j.record("b", "quarantined", error={"type": "ValueError"})
        j.record("b", "ok")  # reprocessed: latest wins
        j.record_event("chunk_halved", chunk=4)
        assert j.done_names() == {"a", "b"}
        assert j.quarantined() == {}
        assert j.summary() == {"ok": 2}
        assert j.events()[0]["event"] == "chunk_halved"
    entries = read_journal(out)
    assert [e.get("name", e.get("event")) for e in entries] == [
        "a", "b", "b", "chunk_halved"
    ]


def test_resume_same_config_loads_entries(tmp_path):
    out = str(tmp_path / "run")
    with RunJournal.open(out, CFG) as j:
        j.record("a", "ok", out="a.box")
        j.record("b", "quarantined", error=error_info(ValueError("x")))
    with RunJournal.open(out, CFG, resume=True) as j2:
        assert j2.resumed
        assert j2.done_names() == {"a"}  # quarantined is NOT done
        assert set(j2.quarantined()) == {"b"}
        j2.record("b", "ok", out="b.box")
        assert j2.done_names() == {"a", "b"}


def test_resume_config_mismatch_discards_journal(tmp_path):
    out = str(tmp_path / "run")
    with RunJournal.open(out, CFG) as j:
        j.record("a", "ok")
    other = dict(CFG, box_size=128)
    with RunJournal.open(out, other, resume=True) as j2:
        assert not j2.resumed
        assert j2.latest() == {}
    # the stale journal file was dropped, not merged
    assert read_journal(out) == []


def test_no_resume_is_fresh_even_with_same_config(tmp_path):
    out = str(tmp_path / "run")
    with RunJournal.open(out, CFG) as j:
        j.record("a", "ok")
    with RunJournal.open(out, CFG, resume=False) as j2:
        assert not j2.resumed and j2.latest() == {}


def test_torn_trailing_line_is_tolerated(tmp_path):
    out = str(tmp_path / "run")
    with RunJournal.open(out, CFG) as j:
        j.record("a", "ok")
        path = j.path
    with open(path, "at") as f:
        f.write('{"name": "b", "status": "o')  # crash mid-write
    with RunJournal.open(out, CFG, resume=True) as j2:
        assert j2.done_names() == {"a"}


def test_manifest_pins_config_json_roundtripped(tmp_path):
    out = str(tmp_path / "run")
    with RunJournal.open(out, {"names": ("a", "b")}) as j:
        j.record("a", "ok")
    # tuple vs list must not defeat resume (JSON normalizes both)
    with RunJournal.open(out, {"names": ["a", "b"]}, resume=True) as j2:
        assert j2.resumed
    with open(os.path.join(out, "_manifest.json")) as f:
        assert json.load(f)["config"] == {"names": ["a", "b"]}


def test_atomic_write_publishes_complete_file(tmp_path):
    p = tmp_path / "x.txt"
    with atomic_write(str(p)) as f:
        f.write("hello")
        assert not p.exists()  # nothing visible until the replace
    assert p.read_text() == "hello"
    assert list(tmp_path.iterdir()) == [p]  # no temp residue


def test_atomic_write_failure_keeps_previous_content(tmp_path):
    p = tmp_path / "x.txt"
    p.write_text("ORIGINAL")
    with pytest.raises(RuntimeError):
        with atomic_write(str(p)) as f:
            f.write("partial garbage")
            raise RuntimeError("crash mid-write")
    assert p.read_text() == "ORIGINAL"
    assert list(tmp_path.iterdir()) == [p]


def test_atomic_write_rejects_append_modes(tmp_path):
    with pytest.raises(ValueError):
        with atomic_write(str(tmp_path / "x"), mode="at"):
            pass
