"""Retry/degradation ladder units: OOM classification, chunk
estimation, capacity escalation, backoff bounds, and the budgeted
solver ladder exact -> lp -> greedy.

The OOM escalation path (`_is_oom_error`, `_auto_chunk`,
`escalate_capacities`) previously had no direct tests; these drive it
through the fault-injection harness so the classifier is pinned
against exactly the exception the harness (and XLA) raises.
"""

import numpy as np
import pytest

from repic_tpu.ops.solver import SolverBudgetExceeded, solve_exact
from repic_tpu.pipeline.consensus import (
    _auto_chunk,
    _is_oom_error,
    escalate_capacities,
)
from repic_tpu.runtime import faults
from repic_tpu.runtime.ladder import (
    RetryPolicy,
    classify_error,
    is_oom_error,
    solve_host_ladder,
)

pytestmark = pytest.mark.faults


# ---- error classification ------------------------------------------


def test_is_oom_error_matches_injected_oom():
    with faults.fault_plan("oom"):
        with pytest.raises(RuntimeError) as ei:
            faults.inject("oom", "chunk:x")
    assert is_oom_error(ei.value)
    assert _is_oom_error(ei.value)  # historical alias, same policy
    assert classify_error(ei.value) == "oom"


def test_is_oom_error_variants():
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert is_oom_error(RuntimeError("Out of memory while trying"))
    assert not is_oom_error(RuntimeError("shape mismatch"))
    assert classify_error(OSError("disk gone")) == "io"
    assert classify_error(ValueError("bad row")) == "error"


# ---- retry policy ---------------------------------------------------


def test_backoff_is_exponential_and_capped():
    p = RetryPolicy(max_retries=5, backoff_base_s=0.1, backoff_cap_s=0.5)
    assert p.backoff(1) == pytest.approx(0.1)
    assert p.backoff(2) == pytest.approx(0.2)
    assert p.backoff(3) == pytest.approx(0.4)
    assert p.backoff(4) == 0.5  # capped
    assert p.backoff(100) == 0.5


# ---- _auto_chunk ----------------------------------------------------


def test_auto_chunk_env_and_axis(monkeypatch):
    monkeypatch.delenv("REPIC_CONSENSUS_CHUNK", raising=False)
    # explicit override is clamped to the workload and the mesh axis
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "3")
    assert _auto_chunk(100, 3, 1024, 4) == 4  # rounded up to axis
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "64")
    assert _auto_chunk(10, 3, 1024, 4) == 12  # clamped to workload
    monkeypatch.delenv("REPIC_CONSENSUS_CHUNK", raising=False)
    # budget path: power of two, multiple of the axis, >= axis
    c = _auto_chunk(1024, 5, 4096, 8)
    assert c % 8 == 0 and c >= 8 and (c & (c - 1)) == 0


# ---- escalate_capacities -------------------------------------------


def test_escalation_no_retry_when_within_capacity():
    d, cap, cc, pc, retry = escalate_capacities(
        np.array([8, 100, 10, 0]), 16, 1024, 64, 1024, has_grid=True
    )
    assert not retry
    assert (d, cap, cc, pc) == (16, 1024, 64, 1024)


def test_escalation_jumps_to_observed_requirement():
    # adjacency 33 > 16 -> next {2^k, 1.5*2^k} bucket above 33 is 48
    d, cap, cc, pc, retry = escalate_capacities(
        np.array([33, 5000, 10, 0]), 16, 1024, 64, 1024, has_grid=False
    )
    assert retry
    assert d == 48
    assert cap >= 5000
    assert cc == 64  # cell capacity untouched off-grid
    assert pc == 1024


def test_escalation_cell_and_partial_are_independent():
    d, cap, cc, pc, retry = escalate_capacities(
        np.array([8, 100, 200, 3000]), 16, 1024, 64, 1024, has_grid=True
    )
    assert retry
    assert (d, cap) == (16, 1024)  # untouched
    assert cc >= 200 and pc >= 3000


# ---- solver budget + ladder ----------------------------------------


def _instance():
    """4 cliques on a shared-vertex chain; optimum picks 0 and 2."""
    mv = np.array([[0, 1], [1, 2], [2, 3], [3, 4]], np.int64)
    w = np.array([2.0, 1.5, 1.0, 0.4])
    return mv, w, 5


def test_solve_exact_budget_zero_raises():
    mv, w, _ = _instance()
    with pytest.raises(SolverBudgetExceeded):
        solve_exact(mv, w, budget_s=-1.0)


def test_solve_exact_node_budget_raises():
    from repic_tpu.ops.solver import solve_exact_py

    mv, w, _ = _instance()
    with pytest.raises(SolverBudgetExceeded):
        solve_exact_py(mv, w, node_limit=1, raise_on_limit=True)
    # default behavior keeps the silent greedy fallback
    picked = solve_exact_py(mv, w, node_limit=1)
    assert picked.dtype == bool


def test_ladder_exact_rung_is_optimal():
    mv, w, nv = _instance()
    picked, used = solve_host_ladder(mv, w, nv, solver="exact")
    assert used == "exact"
    assert list(np.where(picked)[0]) == [0, 2]


def test_ladder_degrades_exact_to_lp_on_injection():
    mv, w, nv = _instance()
    with faults.fault_plan("solver_budget:exact:inf"):
        picked, used = solve_host_ladder(mv, w, nv, solver="exact")
    assert used == "lp"
    assert picked.any()


def test_ladder_degrades_to_greedy_when_exact_and_lp_exhausted():
    mv, w, nv = _instance()
    with faults.fault_plan(
        "solver_budget:exact:inf", "solver_budget:lp:inf"
    ):
        picked, used = solve_host_ladder(mv, w, nv, solver="exact")
    assert used == "greedy"
    assert list(np.where(picked)[0]) == [0, 2]  # greedy is optimal here


def test_ladder_real_time_budget_degrades():
    mv, w, nv = _instance()
    picked, used = solve_host_ladder(
        mv, w, nv, solver="exact", budget_s=-1.0
    )
    assert used == "lp"  # exact rung exceeded its (already-past) budget
    assert picked.any()


def test_ladder_empty_problem():
    picked, used = solve_host_ladder(
        np.zeros((0, 2), np.int64), np.zeros(0), 4, solver="exact"
    )
    assert picked.shape == (0,) and used == "exact"
