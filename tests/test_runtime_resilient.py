"""End-to-end fault-tolerant directory runs: quarantine, retry,
per-micrograph fallback, journaled resume, strict fail-fast, and the
budgeted solver degradation — the acceptance scenario of the
fault-tolerant consensus runtime (docs/robustness.md).
"""

import os

import numpy as np
import pytest

from repic_tpu.pipeline.consensus import run_consensus_dir
from repic_tpu.runtime import faults
from repic_tpu.runtime.journal import read_journal
from repic_tpu.runtime.ladder import RetryPolicy
from repic_tpu.utils import box_io

pytestmark = pytest.mark.faults

FAST = RetryPolicy(max_retries=1, backoff_base_s=0.001,
                   backoff_cap_s=0.002)


def _make_dir(tmp_path, m=6, k=3, n=30, seed=0):
    rng = np.random.default_rng(seed)
    d = tmp_path / "picks"
    for p in range(k):
        (d / f"picker{p}").mkdir(parents=True)
    for i in range(m):
        base = rng.uniform(50, 950, size=(n, 2))
        for p in range(k):
            jit = rng.normal(0, 10, size=base.shape)
            conf = rng.uniform(0.1, 1.0, size=n)
            with open(d / f"picker{p}" / f"mic{i}.box", "wt") as f:
                for (x, y), c in zip(base + jit, conf):
                    f.write(f"{x:.2f}\t{y:.2f}\t64\t64\t{c:.4f}\n")
    return str(d)


def _corrupt(data, name="mic2", picker="picker0"):
    path = os.path.join(data, picker, name + ".box")
    with open(path, "wt") as f:
        f.write("x y w h conf\nthis is not a number at all\n")
    return path


def _boxes(out):
    return {
        f: open(os.path.join(out, f)).read()
        for f in sorted(os.listdir(out))
        if f.endswith(".box")
    }


def test_lenient_run_quarantines_and_resumes(tmp_path, monkeypatch):
    """The acceptance scenario: one corrupt BOX + one injected OOM.

    Lenient mode completes, quarantines exactly the bad micrograph,
    and a follow-up --resume run re-processes only the quarantined
    entry — verified on the journal contents."""
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "2")
    data = _make_dir(tmp_path)
    _corrupt(data, "mic2")
    out = str(tmp_path / "out")

    with faults.fault_plan("oom:chunk:1"):
        stats = run_consensus_dir(
            data, out, 64, use_mesh=False, retry_policy=FAST
        )
        assert faults.fired_log()  # the OOM really fired

    # run completed; exactly the corrupt micrograph was quarantined
    assert sorted(stats["quarantined"]) == ["mic2"]
    info = stats["quarantined"]["mic2"]
    assert info["type"] == "BoxParseError"
    assert "mic2.box" in info["message"]  # actionable: names the file
    assert "mic2" not in stats["particle_counts"]
    assert not os.path.exists(os.path.join(out, "mic2.box"))
    others = [f"mic{i}" for i in range(6) if i != 2]
    assert sorted(stats["particle_counts"]) == sorted(others)

    # journal: quarantine entry + a retried chunk from the OOM rung
    latest = {
        e["name"]: e for e in read_journal(out) if "name" in e
    }
    assert latest["mic2"]["status"] == "quarantined"
    assert latest["mic2"]["error"]["path"].endswith("picker0/mic2.box")
    assert any(
        e["status"] == "retried" for e in latest.values()
    ), "the injected OOM must surface as a retried outcome"
    assert stats["journal"]["quarantined"] == 1

    # fix the input, resume: ONLY the quarantined entry re-processes
    with open(os.path.join(data, "picker0", "mic2.box"), "wt") as f:
        f.write("100 100 64 64 0.9\n150 150 64 64 0.8\n")
    before = len(read_journal(out))
    stats2 = run_consensus_dir(
        data, out, 64, use_mesh=False, resume=True, retry_policy=FAST
    )
    assert stats2["resumed"] == 5
    assert sorted(stats2["particle_counts"]) == ["mic2"]
    assert stats2["quarantined"] == {}
    assert os.path.exists(os.path.join(out, "mic2.box"))
    new_entries = read_journal(out)[before:]
    assert [e["name"] for e in new_entries if "name" in e] == ["mic2"]
    assert new_entries[-1]["status"] == "ok"


def test_injected_corrupt_box_quarantines_then_resumes(tmp_path):
    """Same acceptance scenario, driven purely by injection: the
    corrupt BOX and the OOM both come from the fault plan, and the
    single-shot injection means --resume heals the run without
    touching the input."""
    data = _make_dir(tmp_path, m=4)
    out = str(tmp_path / "out")
    with faults.fault_plan("corrupt_box:mic3", "oom:chunk:1"):
        stats = run_consensus_dir(
            data, out, 64, use_mesh=False, retry_policy=FAST
        )
    assert sorted(stats["quarantined"]) == ["mic3"]
    assert sorted(stats["particle_counts"]) == ["mic0", "mic1", "mic2"]
    stats2 = run_consensus_dir(
        data, out, 64, use_mesh=False, resume=True
    )
    assert stats2["resumed"] == 3
    assert sorted(stats2["particle_counts"]) == ["mic3"]
    latest = {e["name"]: e for e in read_journal(out) if "name" in e}
    assert latest["mic3"]["status"] == "ok"


def test_strict_mode_fails_fast_on_corrupt_input(tmp_path):
    data = _make_dir(tmp_path, m=3)
    _corrupt(data, "mic1")
    out = str(tmp_path / "out")
    with pytest.raises(box_io.BoxParseError, match="mic1.box"):
        run_consensus_dir(data, out, 64, use_mesh=False, strict=True)


def test_strict_mode_fails_fast_on_persistent_oom(tmp_path, monkeypatch):
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "1")
    data = _make_dir(tmp_path, m=3)
    out = str(tmp_path / "out")
    with faults.fault_plan("oom:chunk:inf"):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            run_consensus_dir(data, out, 64, use_mesh=False, strict=True)


def test_per_micrograph_fallback_and_quarantine(tmp_path, monkeypatch):
    """Chunk-level ladder exhausted -> isolate micrographs; the one
    that still fails is quarantined, the rest complete (degraded)."""
    monkeypatch.delenv("REPIC_CONSENSUS_CHUNK", raising=False)
    data = _make_dir(tmp_path, m=4)
    out = str(tmp_path / "out")
    with faults.fault_plan("oom:chunk:inf", "oom:mic:mic1:inf"):
        stats = run_consensus_dir(
            data, out, 64, use_mesh=False, retry_policy=FAST
        )
    assert sorted(stats["quarantined"]) == ["mic1"]
    assert stats["quarantined"]["mic1"]["kind"] == "oom"
    assert sorted(stats["particle_counts"]) == ["mic0", "mic2", "mic3"]
    latest = {e["name"]: e for e in read_journal(out) if "name" in e}
    assert latest["mic1"]["status"] == "quarantined"
    for nm in ("mic0", "mic2", "mic3"):
        assert latest[nm]["status"] == "degraded"
    events = [e["event"] for e in read_journal(out) if "event" in e]
    assert "per_micrograph_fallback" in events


def test_transient_error_retries_then_succeeds(tmp_path, monkeypatch):
    """A transient (non-OOM) chunk failure is retried with backoff
    and the affected micrographs are journaled as retried."""
    monkeypatch.delenv("REPIC_CONSENSUS_CHUNK", raising=False)
    data = _make_dir(tmp_path, m=3)
    out = str(tmp_path / "out")
    with faults.fault_plan("io:chunk:1"):
        stats = run_consensus_dir(
            data, out, 64, use_mesh=False, retry_policy=FAST
        )
    assert stats["quarantined"] == {}
    assert len(stats["particle_counts"]) == 3
    latest = {e["name"]: e for e in read_journal(out) if "name" in e}
    assert all(e["status"] == "retried" for e in latest.values())


def test_crash_then_resume_matches_fresh_run(tmp_path, monkeypatch):
    """Kill a strict run mid-directory; resume completes it and the
    combined outputs are byte-identical to an uninterrupted run."""
    monkeypatch.setenv("REPIC_CONSENSUS_CHUNK", "1")
    data = _make_dir(tmp_path, m=5)
    out = str(tmp_path / "out")
    with faults.fault_plan("oom:chunk:mic3:inf"):
        with pytest.raises(RuntimeError):
            run_consensus_dir(data, out, 64, use_mesh=False, strict=True)
    done_before = set(_boxes(out))
    assert done_before  # the crash landed mid-run, not before it
    assert "mic3.box" not in done_before

    stats = run_consensus_dir(
        data, out, 64, use_mesh=False, resume=True, strict=True
    )
    assert stats["resumed"] == len(done_before)
    out_fresh = str(tmp_path / "fresh")
    monkeypatch.delenv("REPIC_CONSENSUS_CHUNK", raising=False)
    run_consensus_dir(data, out_fresh, 64, use_mesh=False)
    assert _boxes(out) == _boxes(out_fresh)


def test_solver_budget_degradation_is_journaled(tmp_path):
    """exact -> lp -> greedy, with the rung that actually ran
    recorded per micrograph in the journal."""
    data = _make_dir(tmp_path, m=2)

    # no pressure: the exact rung runs and is recorded
    out0 = str(tmp_path / "exact")
    stats = run_consensus_dir(
        data, out0, 64, use_mesh=False, solver="exact"
    )
    latest = {e["name"]: e for e in read_journal(out0) if "name" in e}
    assert all(e["solver"] == "exact" for e in latest.values())
    assert all(e["status"] == "ok" for e in latest.values())
    assert len(stats["particle_counts"]) == 2

    # injected exhaustion of the exact rung: degrade to LP-rounding
    out1 = str(tmp_path / "lp")
    with faults.fault_plan("solver_budget:exact:inf"):
        run_consensus_dir(data, out1, 64, use_mesh=False, solver="exact")
    latest = {e["name"]: e for e in read_journal(out1) if "name" in e}
    assert all(e["solver"] == "lp" for e in latest.values())
    assert all(e["status"] == "degraded" for e in latest.values())

    # exact AND lp exhausted: the terminal greedy rung still lands
    out2 = str(tmp_path / "greedy")
    with faults.fault_plan(
        "solver_budget:exact:inf", "solver_budget:lp:inf"
    ):
        run_consensus_dir(data, out2, 64, use_mesh=False, solver="exact")
    latest = {e["name"]: e for e in read_journal(out2) if "name" in e}
    assert all(e["solver"] == "greedy" for e in latest.values())

    # a REAL (already-expired) wall-clock budget, no injection
    out3 = str(tmp_path / "budget")
    run_consensus_dir(
        data, out3, 64, use_mesh=False, solver="exact",
        solver_budget_s=-1.0,
    )
    latest = {e["name"]: e for e in read_journal(out3) if "name" in e}
    assert all(e["solver"] == "lp" for e in latest.values())
    assert all(e["status"] == "degraded" for e in latest.values())


def test_exact_solver_plain_path_output_format(tmp_path):
    """solver=exact writes reference-format BOX files and never
    selects conflicting cliques."""
    data = _make_dir(tmp_path, m=2, n=20)
    out = str(tmp_path / "out")
    stats = run_consensus_dir(data, out, 64, use_mesh=False,
                              solver="exact")
    for name, count in stats["particle_counts"].items():
        bs = box_io.read_box(os.path.join(out, name + ".box"))
        assert bs.n == count > 0


def test_resume_config_mismatch_restarts_from_scratch(tmp_path):
    """--resume against a DIFFERENT run's out_dir must not leave the
    other run's outputs behind (fresh-run semantics, for real)."""
    data = _make_dir(tmp_path, m=2)
    out = str(tmp_path / "out")
    run_consensus_dir(data, out, 64, use_mesh=False)
    with open(os.path.join(out, "stale_extra.box"), "wt") as f:
        f.write("999 999 64 64 1.0\n")  # pretend: older dataset's file
    stats = run_consensus_dir(
        data, out, 128, use_mesh=False, resume=True  # box_size differs
    )
    assert stats["resumed"] == 0
    assert not os.path.exists(os.path.join(out, "stale_extra.box"))
    assert len(stats["particle_counts"]) == 2


def test_negative_retries_rejected():
    with pytest.raises(ValueError, match="max_retries"):
        RetryPolicy(max_retries=-1)


def test_solver_budget_requires_exact(tmp_path):
    data = _make_dir(tmp_path, m=1)
    with pytest.raises(ValueError, match="solver='exact'"):
        run_consensus_dir(
            data, str(tmp_path / "o"), 64, use_mesh=False,
            solver="lp", solver_budget_s=5.0,
        )


def test_outputs_are_atomic_no_temp_residue(tmp_path):
    data = _make_dir(tmp_path, m=3)
    out = str(tmp_path / "out")
    run_consensus_dir(data, out, 64, use_mesh=False)
    residue = [f for f in os.listdir(out) if ".tmp" in f]
    assert residue == []
