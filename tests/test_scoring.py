"""Tests for the segmentation-mask detection scorer (utils/scoring.py).

The oracle is a direct numpy re-statement of the reference's mask
painting (reference: repic/utils/score_detections.py:28-48): paint
each box into a dense array, then compare pixel-wise.
"""

import numpy as np
import pandas as pd
import pytest

from repic_tpu.utils import scoring


def _oracle(gt, pk, h, w):
    def paint(boxes):
        arr = np.zeros((h, w), np.int16)
        for x, y, bw, bh in boxes:
            arr[max(y, 0): y + bh, max(x, 0): x + bw] = 1
        return arr

    gt_arr, pk_arr = paint(gt), paint(pk)
    num_pos = pk_arr.sum()
    tp = (gt_arr * pk_arr).sum()
    prec = 0.0 if num_pos == 0 else tp / num_pos
    gt_area = gt_arr.sum()
    rec = 0.0 if gt_area == 0 else tp / gt_area
    f1 = 0.0 if prec == rec == 0.0 else 2 * prec * rec / (prec + rec)
    return prec, rec, f1, num_pos / (h * w)


def _df(boxes, conf=None):
    df = pd.DataFrame(boxes, columns=["x", "y", "w", "h"])
    if conf is not None:
        df["conf"] = conf
    return df


def test_identical_sets_score_perfectly():
    boxes = [(10, 10, 20, 20), (50, 50, 20, 20)]
    prec, rec, f1, _ = scoring.get_segmentation_scores(
        _df(boxes), _df(boxes), mrc_w=100, mrc_h=100
    )
    assert prec == rec == f1 == 1.0


def test_disjoint_sets_score_zero():
    prec, rec, f1, pos_frac = scoring.get_segmentation_scores(
        _df([(0, 0, 10, 10)]), _df([(50, 50, 10, 10)]),
        mrc_w=100, mrc_h=100,
    )
    assert prec == rec == f1 == 0.0
    assert pos_frac == pytest.approx(100 / 10000)


def test_random_boxes_match_numpy_oracle():
    rng = np.random.default_rng(0)
    for trial in range(5):
        h = w = 400
        n_gt, n_pk = rng.integers(3, 40, size=2)
        gt = np.column_stack(
            [
                rng.integers(0, w - 30, n_gt),
                rng.integers(0, h - 30, n_gt),
                np.full(n_gt, 30),
                np.full(n_gt, 30),
            ]
        )
        pk = np.column_stack(
            [
                rng.integers(0, w - 30, n_pk),
                rng.integers(0, h - 30, n_pk),
                np.full(n_pk, 30),
                np.full(n_pk, 30),
            ]
        )
        got = scoring.get_segmentation_scores(
            _df(gt), _df(pk), mrc_w=w, mrc_h=h
        )
        want = _oracle(gt, pk, h, w)
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_boxes_overflowing_the_micrograph_are_clipped():
    # numpy slicing clips out-of-range stops; the kernel must too
    got = scoring.get_segmentation_scores(
        _df([(90, 90, 20, 20)]), _df([(90, 90, 20, 20)]),
        mrc_w=100, mrc_h=100,
    )
    assert got[0] == got[1] == 1.0
    assert got[3] == pytest.approx(100 / 10000)


def test_conf_threshold_filters_picker_boxes_only():
    gt = _df([(0, 0, 10, 10)])
    pk = _df([(0, 0, 10, 10), (50, 50, 10, 10)], conf=[0.2, 0.9])
    prec, rec, _, _ = scoring.get_segmentation_scores(
        gt, pk, conf_thresh=0.5, mrc_w=100, mrc_h=100
    )
    # the matching low-conf box is dropped: nothing overlaps gt
    assert prec == 0.0 and rec == 0.0


def test_dims_inferred_from_max_extent():
    gt = _df([(10, 10, 20, 20)])
    pk = _df([(10, 10, 20, 20)])
    prec, rec, f1, pos_frac = scoring.get_segmentation_scores(gt, pk)
    # inferred dims: 30 x 30 (reference: score_detections.py:21-25)
    assert pos_frac == pytest.approx(400 / 900)
    assert prec == rec == 1.0


def test_empty_gt_gives_zero_recall_not_nan():
    got = scoring.get_segmentation_scores(
        _df(np.zeros((0, 4))), _df([(0, 0, 10, 10)]),
        mrc_w=50, mrc_h=50,
    )
    assert got[1] == 0.0 and not np.isnan(got[1])


def test_match_by_stem_allows_picker_suffix():
    pairs = scoring.match_by_stem(
        ["/gt/Mic_A.box", "/gt/mic_b.box"],
        ["/p/mic_a_picked.box", "/p/other.box"],
    )
    assert len(pairs) == 1
    assert pairs[0][0] == "mic_a"


def test_cli_end_to_end(tmp_path):
    gt_dir, p_dir = tmp_path / "gt", tmp_path / "p"
    gt_dir.mkdir(), p_dir.mkdir()
    (gt_dir / "m1.box").write_text("10\t10\t20\t20\t1.0\n")
    (p_dir / "m1.box").write_text("10\t10\t20\t20\t0.9\n")
    from repic_tpu.main import build_parser

    args = build_parser().parse_args(
        [
            "score",
            "-g", str(gt_dir / "m1.box"),
            "-p", str(p_dir / "m1.box"),
            "--out_dir", str(tmp_path / "out"),
        ]
    )
    args.func(args)
    tsv = (tmp_path / "out" / "particle_set_comp.tsv").read_text()
    lines = tsv.strip().splitlines()
    assert lines[0].split("\t") == [
        "filename", "precision", "recall", "f1", "pos_frac"
    ]
    vals = lines[1].split("\t")
    assert vals[0] == "m1"
    assert float(vals[1]) == 1.0


def test_golden_scores_match_executed_reference():
    """Gate the scorer against the reference implementation's actual
    output: tests/golden/ref_scores_cryolo_vs_topaz_10017.tsv was
    produced by EXECUTING reference score_detections.py (crYOLO picks
    as ground truth, topaz picks as detections) on examples/10017."""
    import os

    from tests.conftest import REFERENCE_EXAMPLES, reference_available

    if not reference_available():
        import pytest

        pytest.skip("reference example data not mounted")
    import glob

    from repic_tpu.utils.scoring import score_box_files

    golden_path = os.path.join(
        os.path.dirname(__file__),
        "golden",
        "ref_scores_cryolo_vs_topaz_10017.tsv",
    )
    golden = {}
    with open(golden_path) as f:
        next(f)
        for line in f:
            name, *vals = line.split("\t")
            golden[name] = [float(v) for v in vals]

    rows = score_box_files(
        sorted(glob.glob(os.path.join(REFERENCE_EXAMPLES, "crYOLO", "*.box"))),
        sorted(glob.glob(os.path.join(REFERENCE_EXAMPLES, "topaz", "*.box"))),
    )
    assert len(rows) == len(golden) == 12
    for stem, precision, recall, f1, pos_frac in rows:
        want = golden[stem]
        np.testing.assert_allclose(
            [precision, recall, f1, pos_frac], want, rtol=1e-6,
            err_msg=stem,
        )


def test_star_gt_scored_against_box_picks(tmp_path):
    """STAR ground truth + BOX picks through the format-routing CLI
    (the reference scorer is BOX-only, score_detections.py:53-56)."""
    gt_dir, p_dir = tmp_path / "gt", tmp_path / "p"
    gt_dir.mkdir(), p_dir.mkdir()
    # star is centered: center (20, 20) with box 20 -> corner (10, 10)
    (gt_dir / "m1.star").write_text(
        "data_\n\nloop_\n_rlnCoordinateX #1\n_rlnCoordinateY #2\n"
        "_rlnAutopickFigureOfMerit #3\n20.0\t20.0\t1.0\n"
    )
    (p_dir / "m1.box").write_text("10\t10\t20\t20\t0.9\n")
    from repic_tpu.main import build_parser

    args = build_parser().parse_args(
        [
            "score",
            "-g", str(gt_dir / "m1.star"),
            "-p", str(p_dir / "m1.box"),
            "--gt_format", "star",
            "--box_size", "20",
            "--out_dir", str(tmp_path / "out"),
        ]
    )
    args.func(args)
    lines = (
        (tmp_path / "out" / "particle_set_comp.tsv")
        .read_text().strip().splitlines()
    )
    vals = lines[1].split("\t")
    assert vals[0] == "m1"
    # identical geometry after the center->corner shift: perfect score
    assert float(vals[1]) == 1.0 and float(vals[3]) == 1.0
