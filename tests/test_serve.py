"""Serve daemon tests: lifecycle, admission, deadlines, recovery.

The ISSUE 8 acceptance surface: submit -> poll -> artifacts works;
overload is an explicit 429 + Retry-After; deadlines cancel at chunk
boundaries and journal ``deadline_exceeded``; a ``server_crash``
mid-run loses zero accepted jobs across restart; slow clients hurt
only themselves; the breaker opens on repeated failures.
"""

import http.client
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from repic_tpu.runtime import faults
from repic_tpu.runtime.journal import _read_entries
from repic_tpu.serve.daemon import ConsensusDaemon
from repic_tpu.serve.jobs import (
    JOB_FINISHED,
    SERVE_CRASH_EXIT_CODE,
    AdmissionError,
    CircuitBreaker,
    JobQueue,
    ServeJournal,
)
from repic_tpu.telemetry import server as tlm_server

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "mini10017"
)
SUBMIT = {
    "in_dir": FIXTURE,
    "box_size": 180,
    "options": {"use_mesh": False},
}
TERMINAL = (
    "finished", "failed", "cancelled", "deadline_exceeded",
    "quarantined",
)


def _req(port, method, path, body=None, timeout=30):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=(
            json.dumps(body).encode() if body is not None else None
        ),
    )
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


def _wait_terminal(port, job_id, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        code, _, body = _req(port, "GET", f"/v1/jobs/{job_id}")
        assert code == 200, body
        doc = json.loads(body)
        if doc["state"] in TERMINAL:
            return doc
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never became terminal")


@pytest.fixture
def daemon(tmp_path):
    d = ConsensusDaemon(
        str(tmp_path / "wd"),
        port=0,
        queue_limit=4,
        warmup=False,
        drain_grace_s=10.0,
    )
    d.start()
    yield d
    if not d.queue.draining:
        d.drain()


# -- unit: journal, breaker, queue ------------------------------------


def test_serve_journal_recovery_and_torn_tail(tmp_path):
    j = ServeJournal(str(tmp_path))
    j.record("j1", "queued", request={"a": 1})
    j.record("j2", "queued", request={"a": 2})
    j.record("j1", "running")
    j.record("j2", "running")
    j.record("j2", "finished")
    j.close()
    with open(j.path, "a") as f:
        f.write('{"job": "j3", "state": "que')  # crash mid-append
    recovered = ServeJournal(str(tmp_path)).recover()
    assert [r.id for r in recovered] == ["j1"]
    assert recovered[0].resumed is True  # was running at the crash
    assert recovered[0].request == {"a": 1}


def test_circuit_breaker_state_machine():
    t = {"now": 0.0}
    b = CircuitBreaker(
        threshold=2, cooldown_s=10.0, clock=lambda: t["now"]
    )
    b.check_admission()  # closed: fine
    b.record_failure()
    b.check_admission()  # one failure: still closed
    b.record_failure()
    with pytest.raises(AdmissionError) as exc:
        b.check_admission()
    assert exc.value.http_status == 503
    assert exc.value.retry_after_s >= 1
    t["now"] += 10.1  # cooldown over -> half-open probe allowed
    b.check_admission()
    assert b.state == CircuitBreaker.HALF_OPEN
    b.record_failure()  # probe failed -> straight back open
    with pytest.raises(AdmissionError):
        b.check_admission()
    t["now"] += 10.1
    b.check_admission()
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED
    b.check_admission()


def test_queue_admission_bounds_and_retry_after(tmp_path):
    q = JobQueue(2, ServeJournal(str(tmp_path)))
    q.submit({"r": 1})
    q.submit({"r": 2})
    with pytest.raises(AdmissionError) as exc:
        q.submit({"r": 3})
    assert exc.value.http_status == 429
    assert exc.value.reason == "queue_full"
    assert exc.value.retry_after_s >= 1
    # draining rejects regardless of depth
    q2 = JobQueue(2, ServeJournal(str(tmp_path / "d2")))
    q2.begin_drain()
    with pytest.raises(AdmissionError) as exc:
        q2.submit({"r": 1})
    assert exc.value.http_status == 503
    assert exc.value.reason == "draining"


def test_queue_cancel_and_warm_affinity(tmp_path):
    q = JobQueue(10, ServeJournal(str(tmp_path)))
    a = q.submit({"r": 1}, bucket_hint=128)
    b = q.submit({"r": 2}, bucket_hint=256)
    c = q.submit({"r": 3}, bucket_hint=256)
    # warm bucket 256: b and c jump ahead of a (inside the window)
    assert q.next_job(0.01, last_bucket=256).id == b.id
    assert q.next_job(0.01, last_bucket=256).id == c.id
    d = q.submit({"r": 4}, bucket_hint=256)
    # a was skipped MAX_SKIPS times: fairness forces it next even
    # though d matches the warm bucket
    assert q.next_job(0.01, last_bucket=256).id == a.id
    # cancel a queued job outright
    assert q.cancel(d.id).state == "cancelled"
    assert q.next_job(0.01) is None


def test_cancel_of_popped_but_unmarked_job_is_cooperative(tmp_path):
    """RT301 sweep regression: between next_job's pop and
    mark_running's state write the job still reads QUEUED but is no
    longer in the queue — cancel must set the cooperative flag (and
    not ValueError on the pending remove / lose the worker's copy)."""
    q = JobQueue(10, ServeJournal(str(tmp_path)))
    job = q.submit({"r": 1})
    popped = q.next_job(0.01)
    assert popped is job  # the worker holds it; not yet mark_running
    got = q.cancel(job.id)  # must not raise
    assert got is job
    assert job.cancel_requested is True
    assert job.state == "queued"  # state write is mark_running's
    q.mark_running(job)
    assert job.state == "running"
    assert job.cancel_requested is True  # the cancel was not lost


def test_running_cancel_survives_restart(tmp_path):
    """An acknowledged cancel of a RUNNING job is journaled, so the
    re-run after a crash stops at its first cancel poll instead of
    silently un-cancelling."""
    j = ServeJournal(str(tmp_path))
    q = JobQueue(4, j)
    job = q.submit({"r": 1})
    assert q.next_job(0.01).id == job.id
    q.mark_running(job)
    assert q.cancel(job.id).cancel_requested is True
    j.close()
    rec = ServeJournal(str(tmp_path)).recover()
    assert [r.id for r in rec] == [job.id]
    assert rec[0].resumed is True
    assert rec[0].cancel_requested is True


def test_concurrent_cancel_and_finish_never_resurrect(tmp_path):
    """Journal-ordering regression (PR 9 review): cancel() must
    decide its branch and journal its running-state record under the
    queue lock — deciding from a post-lock re-read of job.state let a
    concurrent finish() interleave, either double-journaling the
    cancel or appending a stale RUNNING record AFTER the terminal one
    (recover() folds to latest state, resurrecting a finished job on
    restart)."""
    import threading

    for _ in range(30):
        wd = str(tmp_path / f"r{_}")
        j = ServeJournal(wd)
        q = JobQueue(4, j)
        job = q.submit({"r": 1})
        assert q.next_job(0.01).id == job.id
        q.mark_running(job)
        go = threading.Barrier(2)

        def do_cancel():
            go.wait(5)
            q.cancel(job.id)

        def do_finish():
            go.wait(5)
            q.finish(job, JOB_FINISHED)

        ts = [
            threading.Thread(target=do_cancel),
            threading.Thread(target=do_finish),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        j.close()
        entries = [
            json.loads(line)
            for line in open(os.path.join(wd, "_serve_journal.jsonl"))
            if '"job"' in line
        ]
        states = [e["state"] for e in entries if e["job"] == job.id]
        # whatever the interleaving: the terminal record is LAST,
        # exactly one of it, and never a doubled cancelled record
        assert states[-1] == JOB_FINISHED, states
        assert states.count(JOB_FINISHED) == 1, states
        assert states.count("cancelled") == 0, states
        # so a restarted daemon recovers nothing
        assert ServeJournal(wd).recover() == []


def test_terminal_jobs_evicted_beyond_cap(tmp_path, monkeypatch):
    """A long-lived daemon must not hold every dead Job forever:
    terminal jobs beyond MAX_TERMINAL drop out of the in-memory map
    (their history stays in the journal and jobs/<id>/)."""
    monkeypatch.setattr(JobQueue, "MAX_TERMINAL", 3)
    q = JobQueue(100, ServeJournal(str(tmp_path)))
    ids = []
    for i in range(5):
        job = q.submit({"r": i})
        ids.append(job.id)
        assert q.next_job(0.01).id == job.id
        q.mark_running(job)
        q.finish(job, JOB_FINISHED)
    assert q.get(ids[0]) is None
    assert q.get(ids[1]) is None
    assert all(q.get(i) is not None for i in ids[2:])
    assert len(q.jobs()) == 3


@pytest.mark.faults
def test_request_storm_fault_forces_queue_full(tmp_path):
    q = JobQueue(100, ServeJournal(str(tmp_path)))
    with faults.fault_plan("request_storm::1"):
        with pytest.raises(AdmissionError) as exc:
            q.submit({"r": 1})
        assert exc.value.http_status == 429
        assert q.submit({"r": 2}).state == "queued"  # plan spent


# -- daemon lifecycle over HTTP ---------------------------------------


def test_submit_poll_artifacts_and_warm_second_job(daemon):
    port = daemon.server.port
    code, _, _ = _req(port, "GET", "/healthz/live")
    assert code == 200
    code, _, body = _req(port, "POST", "/v1/jobs", SUBMIT)
    assert code == 202, body
    jid = json.loads(body)["id"]
    doc = _wait_terminal(port, jid)
    assert doc["state"] == "finished", doc
    assert doc["result"]["particles"] > 0
    assert doc["result"]["journal"] == {"ok": 3}
    code, _, body = _req(port, "GET", f"/v1/jobs/{jid}/artifacts")
    arts = json.loads(body)["artifacts"]
    assert code == 200
    assert arts == ["mic_000.box", "mic_001.box", "mic_002.box"]
    code, _, content = _req(
        port, "GET", f"/v1/jobs/{jid}/artifacts/mic_000.box"
    )
    assert code == 200 and len(content.splitlines()) > 0
    # the parity gate: identical to the file the CLI path writes
    out = os.path.join(daemon.job_dir(jid), "mic_000.box")
    with open(out) as f:
        assert f.read() == content
    # warm second request on the same capacity bucket: the program
    # cache hit counter must move (the ISSUE 8 acceptance metric)
    def _cache(kind):
        _, _, metrics = _req(port, "GET", "/metrics")
        for line in metrics.splitlines():
            if line.startswith(f"repic_program_cache_{kind}"):
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    hits0 = _cache("hits_total")
    code, _, body = _req(port, "POST", "/v1/jobs", SUBMIT)
    jid2 = json.loads(body)["id"]
    assert _wait_terminal(port, jid2)["state"] == "finished"
    assert _cache("hits_total") > hits0
    # job list shows both
    _, _, body = _req(port, "GET", "/v1/jobs")
    assert {j["id"] for j in json.loads(body)["jobs"]} >= {jid, jid2}


def test_worker_survives_journal_failure(daemon, monkeypatch):
    """An exception escaping _run_job (here: the journal's RUNNING
    record failing, which fires before its try block) must not kill
    the sole worker thread — a dead worker behind a live HTTP front
    end would 202 jobs into a queue nothing drains, with every
    health probe green."""
    port = daemon.server.port
    orig = daemon.journal.record
    armed = {"on": True}

    def flaky(job_id, state, **fields):
        if state == "running" and armed["on"]:
            armed["on"] = False
            raise OSError("disk full")
        return orig(job_id, state, **fields)

    monkeypatch.setattr(daemon.journal, "record", flaky)
    code, _, body = _req(port, "POST", "/v1/jobs", SUBMIT)
    assert code == 202, body
    doc = _wait_terminal(port, json.loads(body)["id"])
    assert doc["state"] == "failed", doc
    assert "disk full" in json.dumps(doc["error"])
    # the worker survived: the next job runs to completion
    code, _, body = _req(port, "POST", "/v1/jobs", SUBMIT)
    assert code == 202, body
    doc2 = _wait_terminal(port, json.loads(body)["id"])
    assert doc2["state"] == "finished", doc2
    # and the SLO plane heard about the escape-path failure too —
    # the last-resort branch goes through _finish_job, so /status
    # compliance cannot read 1.0 while every job is dying there
    job_ep = daemon.slo.summary()["endpoints"]["job"]
    assert job_ep["count"] == 2, job_ep


def test_submission_validation_maps_to_400(daemon):
    port = daemon.server.port
    cases = [
        {"box_size": 180},                                # no in_dir
        {"in_dir": "/nonexistent", "box_size": 180},
        {"in_dir": FIXTURE, "box_size": -1},
        {"in_dir": FIXTURE, "box_size": 180, "typo": 1},
        {"in_dir": FIXTURE, "box_size": 180,
         "options": {"typo": 1}},
        {"in_dir": FIXTURE, "box_size": 180, "deadline_s": 0},
    ]
    for body in cases:
        code, _, resp = _req(port, "POST", "/v1/jobs", body)
        assert code == 400, (body, resp)
    code, _, _ = _req(port, "GET", "/v1/jobs/job-nope")
    assert code == 404


def test_readiness_follows_warmup_and_drain(tmp_path):
    d = ConsensusDaemon(
        str(tmp_path / "wd"), port=0, warmup=True
    )
    d.start()
    try:
        port = d.server.port
        assert _req(port, "GET", "/healthz/live")[0] == 200
        deadline = time.time() + 60
        while _req(port, "GET", "/healthz/ready")[0] != 200:
            assert time.time() < deadline, "never became ready"
            time.sleep(0.05)
        # drain phase 1: readiness red, admission 503, port alive
        d.begin_drain()
        assert _req(port, "GET", "/healthz/ready")[0] == 503
        assert _req(port, "GET", "/healthz/live")[0] == 200
        code, headers, body = _req(port, "POST", "/v1/jobs", SUBMIT)
        assert code == 503 and "draining" in body
        assert int(headers["Retry-After"]) >= 1
    finally:
        d.finish_drain()
    with pytest.raises(urllib.error.URLError):
        _req(port, "GET", "/healthz/live", timeout=2)


def test_deadline_expired_while_queued(daemon):
    port = daemon.server.port
    body = dict(SUBMIT, deadline_s=1e-4)
    code, _, resp = _req(port, "POST", "/v1/jobs", body)
    assert code == 202
    doc = _wait_terminal(port, json.loads(resp)["id"])
    assert doc["state"] == "deadline_exceeded"
    assert "queued" in doc["reason"]


@pytest.mark.faults
def test_deadline_fault_cancels_at_chunk_boundary(daemon):
    """The ``deadline_exceeded`` site fires at the worker's chunk-
    boundary cancel poll — the run stops BETWEEN chunks and the
    request journal records ``deadline_exceeded``."""
    port = daemon.server.port
    with faults.fault_plan("deadline_exceeded::1"):
        code, _, resp = _req(port, "POST", "/v1/jobs", SUBMIT)
        assert code == 202
        jid = json.loads(resp)["id"]
        doc = _wait_terminal(port, jid)
    assert doc["state"] == "deadline_exceeded"
    states = [
        e.get("state")
        for e in _read_serve_journal(daemon)
        if e.get("job") == jid
    ]
    assert states == ["queued", "running", "deadline_exceeded"]


@pytest.mark.faults
def test_slow_client_hurts_only_itself(daemon):
    port = daemon.server.port
    code, _, resp = _req(port, "POST", "/v1/jobs", SUBMIT)
    jid = json.loads(resp)["id"]
    assert _wait_terminal(port, jid)["state"] == "finished"
    path = f"/v1/jobs/{jid}/artifacts/mic_000.box"
    with faults.fault_plan("slow_client::1"):
        with pytest.raises(
            (http.client.HTTPException, ConnectionError, OSError)
        ):
            _req(port, "GET", path)
    # the daemon shrugged: same artifact, full payload, next request
    code, _, content = _req(port, "GET", path)
    assert code == 200 and content
    assert _req(port, "GET", "/healthz/live")[0] == 200
    assert json.loads(
        _req(port, "GET", f"/v1/jobs/{jid}")[2]
    )["state"] == "finished"


def _read_serve_journal(daemon):
    from repic_tpu.runtime.journal import _read_entries

    return _read_entries(daemon.journal.path)


def test_queued_job_survives_restart_in_process(tmp_path):
    """A daemon that died right after accepting (journal written,
    worker never started) must run the job on the next start."""
    wd = str(tmp_path / "wd")
    dead = ConsensusDaemon(wd, warmup=False)  # never start()ed
    job = dead.queue.submit(dict(SUBMIT))
    dead.journal.close()
    d2 = ConsensusDaemon(wd, warmup=False).start()
    try:
        doc = _wait_terminal(d2.server.port, job.id)
        assert doc["state"] == "finished"
        arts = os.listdir(d2.job_dir(job.id))
        assert sum(1 for a in arts if a.endswith(".box")) == 3
    finally:
        d2.drain()


@pytest.mark.faults
def test_breaker_opens_after_repeated_failures(tmp_path):
    """Three poisoned jobs (in_dir vanishes after admission) open
    the breaker: the next submission is 503 circuit_open."""
    wd = str(tmp_path / "wd")
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()  # exists at validation, has no picker subdirs
    d = ConsensusDaemon(
        wd, port=0, warmup=False, breaker_threshold=3,
        breaker_cooldown_s=60.0, queue_limit=10,
    )
    d.start()
    try:
        port = d.server.port
        bad = {"in_dir": str(bad_dir), "box_size": 180}
        ids = []
        for _ in range(3):
            code, _, resp = _req(port, "POST", "/v1/jobs", bad)
            assert code == 202
            ids.append(json.loads(resp)["id"])
        for jid in ids:
            assert _wait_terminal(port, jid)["state"] == "failed"
        code, headers, body = _req(port, "POST", "/v1/jobs", SUBMIT)
        assert code == 503, body
        assert "circuit_open" in body
        assert int(headers["Retry-After"]) >= 1
    finally:
        d.drain()


# -- crash recovery (subprocess: server_crash is os._exit) ------------


def _spawn_daemon(wd, env_extra=None, extra_args=()):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        REPIC_TPU_NO_CONFIG_CACHE="1",
        REPIC_CONSENSUS_CHUNK="1",
        **(env_extra or {}),
    )
    env.pop("REPIC_TPU_FAULTS", None)
    if env_extra and "REPIC_TPU_FAULTS" in env_extra:
        env["REPIC_TPU_FAULTS"] = env_extra["REPIC_TPU_FAULTS"]
    proc = subprocess.Popen(
        [sys.executable, "-m", "repic_tpu.main", "serve", wd,
         "--port", "0", "--no-warmup", *extra_args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )
    info_path = os.path.join(wd, "_serve.json")
    deadline = time.time() + 60
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                "daemon died at startup:\n" + proc.communicate()[0]
            )
        try:
            with open(info_path) as f:
                info = json.load(f)
            if info.get("pid") == proc.pid:
                return proc, info["port"]
        except (OSError, ValueError):
            pass
        time.sleep(0.1)
    proc.kill()
    raise AssertionError("daemon never wrote _serve.json")


@pytest.mark.faults
def test_server_crash_recovers_all_accepted_jobs(tmp_path):
    """The acceptance gate: a daemon crash mid-run (server_crash at
    a chunk boundary) loses ZERO accepted jobs — the restarted
    daemon replays the journal, resumes the in-flight job past its
    completed micrographs, and runs the still-queued one."""
    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    proc, port = _spawn_daemon(
        wd, {"REPIC_TPU_FAULTS": "server_crash:chunk:1"}
    )
    try:
        code, _, resp = _req(port, "POST", "/v1/jobs", SUBMIT)
        assert code == 202, resp
        j1 = json.loads(resp)["id"]
        code, _, resp = _req(port, "POST", "/v1/jobs", SUBMIT)
        assert code == 202, resp
        j2 = json.loads(resp)["id"]
        # the fault kills the daemon at job 1's first chunk boundary
        assert proc.wait(timeout=120) == SERVE_CRASH_EXIT_CODE
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    proc2, port2 = _spawn_daemon(wd)
    try:
        d1 = _wait_terminal(port2, j1, timeout=180)
        d2 = _wait_terminal(port2, j2, timeout=180)
        assert d1["state"] == "finished", d1
        assert d2["state"] == "finished", d2
        assert d1["resumed"] is True  # was in flight at the crash
        for jid in (j1, j2):
            _, _, body = _req(
                port2, "GET", f"/v1/jobs/{jid}/artifacts"
            )
            assert len(json.loads(body)["artifacts"]) == 3, jid
        # the resumed job really resumed: generation 2 only
        # processed what generation 1 had not journaled as done
        assert d1["result"]["resumed_micrographs"] >= 1, d1
    finally:
        proc2.terminate()
        try:
            proc2.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc2.kill()
        proc2.communicate()


# -- journal compaction (ISSUE 14) ------------------------------------


def test_journal_compaction_folds_old_terminal_jobs(tmp_path):
    """Old terminal jobs fold to one record each; every record of
    every open job survives verbatim (resumed/cancel flags
    included); idempotency keys ride the folded record; a second
    compaction is a no-op."""
    j = ServeJournal(str(tmp_path))
    for i in range(6):
        jid = f"t{i}"
        j.record(jid, "queued", request={"n": i},
                 idempotency_key=f"key-{i}", tenant="teamA")
        j.record(jid, "running")
        j.record(jid, "finished", particles=i)
    j.record("open-q", "queued", request={"n": "q"})
    j.record("open-r", "queued", request={"n": "r"})
    j.record("open-r", "running")
    j.record("open-r", "running", cancel_requested=True)
    j.record_event("warmup", programs_warmed=1)
    j.close()
    with open(j.path, "a") as f:
        f.write('{"job": "torn", "state": "que')  # crash tail
    stats = ServeJournal(str(tmp_path)).compact(max_terminal=2)
    assert stats["folded"] == 4  # 6 terminal - newest 2
    entries = _read_entries(j.path)
    # folded jobs: exactly one record, terminal, key+tenant carried,
    # request payload dropped
    for i in range(4):
        recs = [e for e in entries if e.get("job") == f"t{i}"]
        assert len(recs) == 1, recs
        assert recs[0]["state"] == "finished"
        assert recs[0]["folded"] is True
        assert recs[0]["idempotency_key"] == f"key-{i}"
        assert recs[0]["tenant"] == "teamA"
        assert "request" not in recs[0]
    # the newest 2 terminal jobs keep their full history
    for i in (4, 5):
        recs = [e for e in entries if e.get("job") == f"t{i}"]
        assert len(recs) == 3, recs
        assert recs[0]["request"] == {"n": i}
    # recovery semantics are untouched: same open jobs, same flags
    rec = {r.id: r for r in ServeJournal(str(tmp_path)).recover()}
    assert set(rec) == {"open-q", "open-r"}
    assert rec["open-r"].resumed is True
    assert rec["open-r"].cancel_requested is True
    assert rec["open-q"].request == {"n": "q"}
    # idempotent: nothing left to fold
    assert (
        ServeJournal(str(tmp_path)).compact(max_terminal=2)
        is None
    )


def test_compaction_runs_on_daemon_start(tmp_path, monkeypatch):
    """A restarted daemon starts against a bounded journal: the
    folded terminal jobs stay terminal (never re-queued) and the
    queued job still runs."""
    wd = str(tmp_path / "wd")
    j = ServeJournal(wd)
    for i in range(5):
        j.record(f"t{i}", "queued", request={"n": i})
        j.record(f"t{i}", "finished")
    j.close()
    monkeypatch.setattr(JobQueue, "MAX_TERMINAL", 2)
    d = ConsensusDaemon(wd, port=0, warmup=False)
    d.start()
    try:
        entries = _read_serve_journal(d)
        assert any(
            e.get("event") == "journal_compacted" for e in entries
        )
        folded = [
            e for e in entries
            if e.get("folded") is True and e.get("job")
        ]
        assert len(folded) == 3
        # nothing resurrected
        assert all(
            j.state in TERMINAL
            for j in d.queue.jobs()
            if j.id.startswith("t")
        )
    finally:
        d.drain()


def test_compaction_folds_peer_terminal_jobs_via_hint(tmp_path):
    """Fleet review fix: a job accepted here but finished on a PEER
    has no local terminal record — the merged-view terminal hint
    still folds it (last local record kept, ts intact, so the
    peer's terminal record keeps winning the merged fold)."""
    j = ServeJournal(str(tmp_path), replica="a")
    for i in range(4):
        j.record(f"p{i}", "queued", request={"n": i},
                 idempotency_key=f"k{i}")
    j.record("open", "queued", request={"n": "o"})
    j.close()
    stats = ServeJournal(str(tmp_path), replica="a").compact(
        max_terminal=1, terminal_ids={f"p{i}" for i in range(4)}
    )
    assert stats["folded"] == 3  # 4 hinted-terminal - newest 1
    entries = _read_entries(j.path)
    for i in range(3):
        recs = [e for e in entries if e.get("job") == f"p{i}"]
        assert len(recs) == 1 and recs[0]["folded"] is True
        assert recs[0]["state"] == "queued"  # last LOCAL record
        assert recs[0]["idempotency_key"] == f"k{i}"
        assert "request" not in recs[0]
    # the open (un-hinted) job is untouched
    assert any(
        e.get("job") == "open" and "request" in e
        for e in entries
    )


def test_rerun_records_do_not_bill_the_retry_budget(tmp_path):
    """Review fix: the batcher's coalesce-fallback demotion journals
    a same-process `rerun` running record — it is not a crashed
    generation and must not consume the quarantine budget."""
    j = ServeJournal(str(tmp_path))
    j.record("jx", "queued", request={})
    j.record("jx", "running")
    for _ in range(3):
        j.record("jx", "running", rerun=True)
    j.close()
    (job,) = ServeJournal(str(tmp_path)).recover()
    assert job.attempts == 1
    # and the queue's mark_running emits the flag on a demotion
    q = JobQueue(4, ServeJournal(str(tmp_path / "q")))
    job2 = q.submit({"r": 1})
    assert q.next_job(0.01).id == job2.id
    q.mark_running(job2)
    q.mark_running(job2)  # same-process re-run (fallback shape)
    runs = [
        e
        for e in _read_entries(q.journal.path)
        if e.get("state") == "running"
    ]
    assert len(runs) == 2
    assert not runs[0].get("rerun")
    assert runs[1].get("rerun") is True


# -- single-replica poison-job quarantine (ISSUE 14) ------------------


def test_recover_quarantines_job_over_retry_budget(tmp_path):
    """The single-replica half of the retry budget: a journaled
    in-flight job that already crashed `budget + 1` generations is
    quarantined at startup — terminal, exactly one terminal record,
    visible over the API — and the daemon serves other jobs."""
    from repic_tpu.serve.jobs import JOB_QUARANTINED

    wd = str(tmp_path / "wd")
    j = ServeJournal(wd)
    j.record("poison", "queued", request=dict(SUBMIT),
             trace="t-poison")
    for _ in range(3):  # three crashed generations
        j.record("poison", "running")
    j.close()
    d = ConsensusDaemon(wd, port=0, warmup=False,
                        reassign_budget=2)
    d.start()
    try:
        port = d.server.port
        code, _, body = _req(port, "GET", "/v1/jobs/poison")
        assert code == 200
        doc = json.loads(body)
        assert doc["state"] == JOB_QUARANTINED, doc
        assert "retry budget" in doc["reason"]
        assert doc["attempts"] == 3
        states = [
            e["state"]
            for e in _read_serve_journal(d)
            if e.get("job") == "poison" and "event" not in e
        ]
        assert states.count(JOB_QUARANTINED) == 1
        assert states[-1] == JOB_QUARANTINED
        # the daemon is healthy: a fresh job runs to completion
        code, _, body = _req(port, "POST", "/v1/jobs", SUBMIT)
        assert code == 202, body
        doc2 = _wait_terminal(port, json.loads(body)["id"])
        assert doc2["state"] == "finished", doc2
    finally:
        d.drain()


def test_recover_requeues_job_within_budget(tmp_path):
    """One crashed generation is WITHIN the default budget: the job
    re-runs with resume semantics, exactly as before ISSUE 14."""
    wd = str(tmp_path / "wd")
    j = ServeJournal(wd)
    j.record("ok-job", "queued", request=dict(SUBMIT))
    j.record("ok-job", "running")
    j.close()
    d = ConsensusDaemon(wd, port=0, warmup=False)
    d.start()
    try:
        doc = _wait_terminal(d.server.port, "ok-job")
        assert doc["state"] == "finished", doc
        assert doc["resumed"] is True
    finally:
        d.drain()


@pytest.mark.faults
def test_poison_job_fault_exits_26_and_quarantines_on_restart(
    tmp_path,
):
    """End-to-end over real processes: the ``poison_job`` fault
    kills the daemon (exit 26) on the first attempt; the restarted
    daemon — same fault plan still armed, budget 0 — quarantines
    the job at recovery instead of crashing again, and stays up."""
    from repic_tpu.serve.jobs import (
        JOB_QUARANTINED,
        POISON_CRASH_EXIT_CODE,
    )

    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    plan = "poison_job:mini10017:inf"
    proc, port = _spawn_daemon(
        wd,
        {"REPIC_TPU_FAULTS": plan},
        extra_args=["--reassign-budget", "0"],
    )
    try:
        jid = None
        try:
            code, _, resp = _req(port, "POST", "/v1/jobs", SUBMIT)
            assert code == 202, resp
            jid = json.loads(resp)["id"]
        except (
            http.client.HTTPException, ConnectionError, OSError
        ):
            # the pill can kill the daemon while the 202 is still
            # in flight — the torn-202 window journal-before-202
            # exists for: the accept record is already durable
            pass
        assert proc.wait(timeout=120) == POISON_CRASH_EXIT_CODE
        if jid is None:
            queued = [
                e["job"]
                for e in _read_entries(
                    os.path.join(wd, "_serve_journal.jsonl")
                )
                if e.get("state") == "queued"
            ]
            assert len(queued) == 1, queued
            jid = queued[0]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    proc2, port2 = _spawn_daemon(
        wd,
        {"REPIC_TPU_FAULTS": plan},
        extra_args=["--reassign-budget", "0"],
    )
    try:
        doc = _wait_terminal(port2, jid, timeout=60)
        assert doc["state"] == JOB_QUARANTINED, doc
        assert doc["attempts"] == 1
        # the poison is contained: the daemon still serves — the
        # same INPUT in a fresh job would re-fire the plan, so
        # prove liveness via the health and job surfaces instead
        assert _req(port2, "GET", "/healthz/live")[0] == 200
        code, _, body = _req(port2, "GET", "/v1/jobs")
        assert code == 200
    finally:
        proc2.terminate()
        try:
            proc2.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc2.kill()
        proc2.communicate()


def test_statusserver_readiness_endpoints_standalone():
    srv = tlm_server.StatusServer(port=0).start()
    try:
        port = srv.port
        assert _req(port, "GET", "/healthz")[0] == 200
        assert _req(port, "GET", "/healthz/live")[0] == 200
        assert _req(port, "GET", "/healthz/ready")[0] == 503
        tlm_server.set_ready(True)
        assert _req(port, "GET", "/healthz/ready")[0] == 200
        tlm_server.set_ready(False)
        assert _req(port, "GET", "/healthz/ready")[0] == 503
    finally:
        srv.stop()
