"""Fuzz/property tests for the untrusted job-payload boundary.

The serve contract: a malformed POST /v1/jobs body can only ever
cost the client a 400 — never a 5xx, never a worker crash, never an
unbounded buffer.  These tests hold the two validation layers
(:func:`repic_tpu.serve.daemon.validate_submission` and
:meth:`repic_tpu.pipeline.engine.ConsensusOptions.from_dict`) to
"ValueError or a valid result, nothing else" under a seeded
generative sweep (malformed JSON, wrong types everywhere, oversized
fields, non-finite numbers, deep nesting), then round-trips a
selection over real HTTP to pin the 400 mapping.
"""

import itertools
import json
import math
import os
import random
import string

import pytest

from repic_tpu.pipeline.engine import ConsensusOptions
from repic_tpu.serve.daemon import (
    MAX_BODY_BYTES,
    validate_submission,
)

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "mini10017"
)

#: every field either validator knows, plus traps
FIELDS = (
    "in_dir", "box_size", "options", "deadline_s", "bucket_hint",
    "idempotency_key", "typo_field", "__proto__",
)
OPTION_FIELDS = (
    "threshold", "max_neighbors", "num_particles", "use_mesh",
    "spatial", "solver", "use_pallas", "strict", "max_retries",
    "nope",
)


def _weird_values(rng):
    """A generator of adversarial JSON-representable values."""
    deep = x = []
    for _ in range(40):
        x.append([])
        x = x[0]
    return [
        None, True, False, 0, -1, 1, 2**63, -(2**63),
        0.0, -0.0, 1e308, -1e308, float("inf"), float("-inf"),
        float("nan"), 0.3, "", "x", "0.5", "greedy", "exact",
        "\x00", "‮", "a" * 10_000, [], [[]], deep, {}, {"": ""},
        {"a": {"b": {"c": 1}}}, [1, 2, 3], ["a"], [None],
        rng.random(), rng.randint(-(10**9), 10**9),
        "".join(
            rng.choice(string.printable) for _ in range(20)
        ),
    ]


def _check_validate(body: bytes):
    """The property: ValueError (-> 400) or a well-formed tuple."""
    try:
        out = validate_submission(body)
    except ValueError:
        return None
    request, options, deadline_s, hint, key = out
    assert isinstance(request, dict)
    assert isinstance(options, ConsensusOptions)
    assert deadline_s is None or (
        isinstance(deadline_s, float)
        and math.isfinite(deadline_s)
        and deadline_s > 0
    )
    assert hint is None or (isinstance(hint, int) and hint >= 1)
    assert key is None or (isinstance(key, str) and key)
    return out


def test_options_from_dict_never_crashes_on_weird_values():
    rng = random.Random(1234)
    values = _weird_values(rng)
    for field in OPTION_FIELDS:
        for v in values:
            try:
                opts = ConsensusOptions.from_dict({field: v})
            except ValueError:
                continue
            # accepted values must round-trip as sane types
            assert isinstance(opts.threshold, (int, float))
            assert not isinstance(opts.use_mesh, str)


def test_options_from_dict_rejects_wrong_types_explicitly():
    bad = [
        {"threshold": "0.5"},
        {"threshold": [0.5]},
        {"threshold": True},
        {"threshold": float("nan")},
        {"threshold": float("inf")},
        {"threshold": 0.0},
        {"threshold": 2.0},
        {"max_neighbors": 0},
        {"max_neighbors": 1.5},
        {"max_neighbors": False},
        {"max_neighbors": 10**9},
        {"num_particles": -3},
        {"num_particles": "many"},
        {"use_mesh": 1},
        {"use_mesh": "yes"},
        {"strict": None},
        {"spatial": "auto"},
        {"solver": 5},
        {"solver": "exact"},
        {"max_retries": -1},
        {"max_retries": 3.5},
        {"unknown_knob": 1},
        "not a dict",
        [("threshold", 0.5)],
    ]
    for payload in bad:
        with pytest.raises(ValueError):
            ConsensusOptions.from_dict(payload)


def test_options_from_dict_accepts_the_valid_envelope():
    opts = ConsensusOptions.from_dict(
        {
            "threshold": 0.3,
            "max_neighbors": 16,
            "num_particles": 100,
            "use_mesh": False,
            "spatial": None,
            "solver": "lp",
            "use_pallas": False,
            "strict": True,
            "max_retries": 2,
        }
    )
    assert opts.solver == "lp"
    assert opts.strict is True


def test_validate_submission_malformed_bytes_yield_400():
    cases = [
        b"",
        b"not json",
        b"[]",
        b'"a string"',
        b"123",
        b"null",
        b"{",
        b'{"in_dir": }',
        b"\xff\xfe\x00garbage",
        '{"in_dir": "‮"}'.encode(),
        b'{"in_dir": "/tmp", "box_size": 180, "box_size": 190',
        json.dumps({"in_dir": FIXTURE}).encode(),  # no box_size
        # falsy wrong-typed options must NOT coerce to defaults
        json.dumps(
            {"in_dir": FIXTURE, "box_size": 180, "options": []}
        ).encode(),
        json.dumps(
            {"in_dir": FIXTURE, "box_size": 180, "options": 0}
        ).encode(),
        json.dumps(
            {"in_dir": FIXTURE, "box_size": 180, "options": False}
        ).encode(),
        # JSON-level Infinity/NaN literals (json.loads accepts them)
        b'{"in_dir": "%s", "box_size": Infinity}'
        % FIXTURE.encode(),
        b'{"in_dir": "%s", "box_size": NaN}' % FIXTURE.encode(),
        b'{"in_dir": "%s", "box_size": 180, "deadline_s": '
        b"Infinity}" % FIXTURE.encode(),
    ]
    for body in cases:
        try:
            out = validate_submission(body)
        except ValueError:
            continue
        raise AssertionError(f"accepted {body[:60]!r}: {out}")


def test_validate_submission_oversized_fields_yield_400():
    huge = {"in_dir": FIXTURE, "box_size": 180}
    with pytest.raises(ValueError):
        validate_submission(b"x" * (MAX_BODY_BYTES + 1))
    with pytest.raises(ValueError):
        validate_submission(
            json.dumps(dict(huge, in_dir="/" + "a" * 5000)).encode()
        )
    with pytest.raises(ValueError):
        validate_submission(
            json.dumps(dict(huge, box_size=[180] * 100)).encode()
        )
    with pytest.raises(ValueError):
        validate_submission(
            json.dumps(
                dict(huge, idempotency_key="k" * 500)
            ).encode()
        )
    # at the boundary: still valid
    ok = validate_submission(
        json.dumps(dict(huge, idempotency_key="k" * 200)).encode()
    )
    assert ok[4] == "k" * 200


def test_validate_submission_generative_sweep():
    """Seeded sweep: random field/value combinations (plus raw byte
    mutations of a valid body) must satisfy the 400-or-valid
    property — no TypeError, KeyError, RecursionError, OSError out
    of the validator."""
    rng = random.Random(20260803)
    values = _weird_values(rng)
    # single-field corruption over a valid base
    base = {"in_dir": FIXTURE, "box_size": 180}
    for field, v in itertools.product(FIELDS, values):
        payload = dict(base)
        payload[field] = v
        _check_validate(
            json.dumps(payload, default=str).encode()
        )
    # options-field corruption
    for field, v in itertools.product(OPTION_FIELDS, values):
        payload = dict(base, options={field: v})
        _check_validate(
            json.dumps(payload, default=str).encode()
        )
    # random byte mutations of a valid body
    valid = json.dumps(
        dict(base, options={"use_mesh": False}, deadline_s=5)
    ).encode()
    for _ in range(300):
        body = bytearray(valid)
        for _ in range(rng.randint(1, 4)):
            pos = rng.randrange(len(body))
            body[pos] = rng.randrange(256)
        _check_validate(bytes(body))
    # random full-random bodies
    for _ in range(200):
        n = rng.randint(0, 64)
        _check_validate(
            bytes(rng.randrange(256) for _ in range(n))
        )


def test_tenants_file_parser_fuzz():
    """ISSUE 14: the tenants keyfile is operator-supplied untrusted
    input — the parser must be 'ValueError or a valid spec list,
    never any other exception' over adversarial documents."""
    from repic_tpu.serve.tenancy import TenantSpec, parse_tenants

    rng = random.Random(20260804)
    values = _weird_values(rng)
    fields = (
        "name", "keys", "rate", "burst", "max_open_jobs",
        "max_queued_micrographs", "nope",
    )
    base = {"name": "teamA", "keys": ["ka"]}

    def check(doc):
        try:
            specs = parse_tenants(doc)
        except ValueError:
            return
        assert isinstance(specs, list)
        assert all(isinstance(s, TenantSpec) for s in specs)

    for field, v in itertools.product(fields, values):
        entry = dict(base)
        entry[field] = v
        check({"tenants": [entry]})
    # whole-document corruption
    for v in values:
        check(v)
        check({"tenants": v})
    # random multi-tenant documents
    for _ in range(200):
        n = rng.randint(0, 4)
        doc = {
            "tenants": [
                {
                    rng.choice(fields): rng.choice(values),
                    "name": rng.choice(
                        ["teamA", "teamA", "x y", "", 7]
                    ),
                    "keys": rng.choice(
                        [["k"], ["k", "k"], [], "k", [1]]
                    ),
                }
                for _ in range(n)
            ]
        }
        check(doc)


def test_authorization_header_fuzz():
    """resolve() over arbitrary header strings: AuthError (401/403)
    or a tenant name, never a crash — the serve worker must outlive
    any credential a client can type."""
    from repic_tpu.serve.tenancy import (
        AuthError,
        TenantRegistry,
        TenantSpec,
    )

    reg = TenantRegistry(
        [
            TenantSpec(name="teamA", keys=("ka",)),
            TenantSpec(name="anonymous"),
        ]
    )
    rng = random.Random(4321)
    headers = [
        None, "", " ", "Bearer", "Bearer ", "Bearer ka",
        "bearer ka", "BEARER ka", "Basic a2E=", "Bearer ka extra",
        "Bearer\tka", "Bearer \x00", "Bearer " + "k" * 10_000,
        "‮", "Bearer ‮", "ka", ": Bearer ka",
    ] + [
        "".join(
            rng.choice(string.printable) for _ in range(
                rng.randint(0, 40)
            )
        )
        for _ in range(300)
    ]
    names = set()
    for h in headers:
        try:
            name = reg.resolve(h)
        except AuthError as e:
            assert e.http_status in (401, 403), h
            continue
        names.add(name)
        assert name in ("teamA", "anonymous"), (h, name)
    assert "teamA" in names  # the real key did resolve


def test_http_auth_fuzz_worker_survives(tmp_path):
    """Garbage Authorization headers over real HTTP: every answer
    is 401/403 (never 5xx), and a correctly-keyed job still runs."""
    import time as _time
    import urllib.error
    import urllib.request

    from repic_tpu.serve.daemon import ConsensusDaemon
    from repic_tpu.serve.tenancy import TenantRegistry, TenantSpec

    d = ConsensusDaemon(
        str(tmp_path / "wd"),
        port=0,
        warmup=False,
        queue_limit=4,
        tenants=TenantRegistry(
            [TenantSpec(name="teamA", keys=("ka",))]
        ),
    )
    d.start()
    try:
        port = d.server.port
        sub = json.dumps(
            {"in_dir": FIXTURE, "box_size": 180,
             "options": {"use_mesh": False}}
        ).encode()

        def post(auth):
            headers = (
                {} if auth is None else {"Authorization": auth}
            )
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/jobs",
                method="POST", data=sub, headers=headers,
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, r.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        for auth in (
            None, "", "Bearer", "Bearer nope", "Basic xx",
            "Bearer " + "k" * 5000, "Bearer \x7f\x01",
        ):
            code, body = post(auth)
            assert code in (401, 403), (auth, code, body)
        code, body = post("Bearer ka")
        assert code == 202, body
        jid = json.loads(body)["id"]
        deadline = _time.time() + 120
        while _time.time() < deadline:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/jobs/{jid}",
                headers={"Authorization": "Bearer ka"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                doc = json.loads(r.read().decode())
            if doc["state"] not in ("queued", "running"):
                break
            _time.sleep(0.05)
        assert doc["state"] == "finished", doc
    finally:
        d.drain()


def test_http_maps_validation_to_400_and_413(tmp_path):
    """Round-trip a malicious selection over real HTTP: the daemon
    answers 400 (or 413 for an oversized body) and the worker stays
    alive to run a valid job afterwards."""
    import urllib.error
    import urllib.request

    from repic_tpu.serve.daemon import ConsensusDaemon

    d = ConsensusDaemon(
        str(tmp_path / "wd"), port=0, warmup=False, queue_limit=4
    )
    d.start()
    try:
        port = d.server.port

        def post(raw: bytes):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/jobs",
                method="POST",
                data=raw,
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, r.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        bad = [
            b"not json",
            b"[1, 2, 3]",
            json.dumps({"in_dir": FIXTURE}).encode(),
            json.dumps(
                {"in_dir": FIXTURE, "box_size": 180,
                 "options": {"threshold": "NaN"}}
            ).encode(),
            json.dumps(
                {"in_dir": FIXTURE, "box_size": [180, -1]}
            ).encode(),
            b'{"in_dir": "%s", "box_size": Infinity}'
            % FIXTURE.encode(),
        ]
        for raw in bad:
            code, body = post(raw)
            assert code == 400, (raw[:60], code, body)
        # an oversized body is refused before buffering: a 413 when
        # the client manages to read it, or a dropped connection if
        # the server's refusal lands while the client is still
        # sending — either way the daemon never buffers the payload
        try:
            code, _ = post(b"x" * (5 << 20))
            assert code == 413
        except (urllib.error.URLError, OSError):
            pass
        # a negative (or garbage) Content-Length must not reach
        # read(-1) and buffer until the client hangs up
        import http.client

        for bad_len in ("-1", "nope"):
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=30
            )
            try:
                conn.putrequest("POST", "/v1/jobs")
                conn.putheader("Content-Length", bad_len)
                conn.endheaders()
                resp = conn.getresponse()
                assert resp.status in (400, 413), (
                    bad_len, resp.status
                )
            finally:
                conn.close()
        # the worker survived all of it: a valid job still runs
        code, body = post(
            json.dumps(
                {"in_dir": FIXTURE, "box_size": 180,
                 "options": {"use_mesh": False}}
            ).encode()
        )
        assert code == 202, body
        jid = json.loads(body)["id"]
        import time as _time

        deadline = _time.time() + 120
        while _time.time() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/jobs/{jid}", timeout=30
            ) as r:
                doc = json.loads(r.read().decode())
            if doc["state"] not in ("queued", "running"):
                break
            _time.sleep(0.05)
        assert doc["state"] == "finished", doc
    finally:
        d.drain()
