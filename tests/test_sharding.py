"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp

from repic_tpu.parallel.batching import pad_batch, bucket_size
from repic_tpu.parallel.mesh import consensus_mesh, MICROGRAPH_AXIS
from repic_tpu.pipeline.consensus import (
    consensus_one,
    run_consensus_batch,
)
from repic_tpu.utils.box_io import BoxSet
from tests.test_cliques import random_sets


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def _to_boxsets(sets):
    return [
        BoxSet(
            xy=np.array([(x, y) for x, y, _ in s], np.float32),
            conf=np.array([c for _, _, c in s], np.float32),
            wh=np.zeros((len(s), 2), np.float32),
        )
        for s in sets
    ]


def test_bucket_size():
    # {2^k, 1.5 * 2^k} steps: halfway buckets cap padding waste at 33%
    assert bucket_size(1) == 64
    assert bucket_size(65) == 96
    assert bucket_size(97) == 128
    assert bucket_size(700) == 768
    assert bucket_size(800) == 1024
    assert bucket_size(64) == 64
    assert bucket_size(96) == 96


def test_batch_padding_to_mesh(rng):
    micros = [
        (f"m{i}", _to_boxsets(random_sets(rng, 3, 20 + i)))
        for i in range(5)
    ]
    batch = pad_batch(micros, pad_micrographs_to=8)
    assert batch.xy.shape[0] == 8
    assert batch.num_micrographs == 5
    assert not batch.mask[5:].any()


def test_sharded_equals_single_device(rng):
    micros = [
        (f"m{i}", _to_boxsets(random_sets(rng, 3, 30)))
        for i in range(8)
    ]
    batch = pad_batch(micros, pad_micrographs_to=8)
    res_mesh = run_consensus_batch(batch, 180.0, use_mesh=True)
    res_single = run_consensus_batch(batch, 180.0, use_mesh=False)
    np.testing.assert_array_equal(
        np.asarray(res_mesh.picked), np.asarray(res_single.picked)
    )
    np.testing.assert_allclose(
        np.asarray(res_mesh.w), np.asarray(res_single.w), rtol=1e-6
    )


def test_padded_micrographs_produce_no_cliques(rng):
    micros = [("m0", _to_boxsets(random_sets(rng, 3, 30)))]
    batch = pad_batch(micros, pad_micrographs_to=8)
    res = run_consensus_batch(batch, 180.0, use_mesh=True)
    num = np.asarray(res.num_cliques)
    assert (num[1:] == 0).all()
    assert not np.asarray(res.picked)[1:].any()


def test_output_sharding_layout(rng):
    micros = [
        (f"m{i}", _to_boxsets(random_sets(rng, 3, 16)))
        for i in range(8)
    ]
    batch = pad_batch(micros, pad_micrographs_to=8)
    mesh = consensus_mesh()
    from repic_tpu.pipeline.consensus import make_batched_consensus
    from repic_tpu.parallel.mesh import shard_over_micrographs

    fn = make_batched_consensus(mesh=mesh)
    xy, conf, mask = shard_over_micrographs(
        mesh, batch.xy, batch.conf, batch.mask
    )
    res = fn(xy, conf, mask, 180.0)
    spec = res.picked.sharding.spec
    assert spec[0] == MICROGRAPH_AXIS


def test_distributed_single_process_noop():
    """initialize() is a clean no-op outside a multi-process launch."""
    from repic_tpu.parallel import distributed

    assert distributed.initialize() is False


def test_shard_for_process_partitions():
    from repic_tpu.parallel import distributed

    items = list(range(10))
    shards = [
        distributed.shard_for_process(items, process_id=i, process_count=3)
        for i in range(3)
    ]
    flat = [x for s in shards for x in s]
    assert flat == items  # disjoint, covering, ordered


def test_assemble_global_batch_roundtrip():
    """Single-process 'multi-host' assembly: local data lands sharded
    over the mesh with values intact."""
    import numpy as np

    from repic_tpu.parallel import distributed
    from repic_tpu.parallel.mesh import consensus_mesh

    mesh = consensus_mesh()
    n_dev = len(mesh.devices.reshape(-1))
    local = np.arange(n_dev * 4, dtype=np.float32).reshape(n_dev, 4)
    (g,) = distributed.assemble_global_batch(mesh, (local,))
    assert g.shape == (n_dev, 4)
    np.testing.assert_array_equal(np.asarray(g), local)
