"""Set-packing solver tests: parallel greedy == sequential greedy,
exact branch-and-bound == brute force, greedy quality bound."""

import itertools

import numpy as np
import jax.numpy as jnp

from repic_tpu.ops.solver import solve_exact_py, solve_greedy


def sequential_greedy(member_vertex, w, valid):
    """Oracle: greedy in (w desc, index asc) order."""
    order = np.lexsort((np.arange(len(w)), -w))
    used = set()
    picked = np.zeros(len(w), bool)
    for c in order:
        if not valid[c] or w[c] <= 0:
            continue
        verts = set(int(v) for v in member_vertex[c])
        if used & verts:
            continue
        picked[c] = True
        used |= verts
    return picked


def brute_force_exact(member_vertex, w):
    best_val, best_sel = -1.0, None
    n = len(w)
    for bits in itertools.product([0, 1], repeat=n):
        used = set()
        ok = True
        val = 0.0
        for c in range(n):
            if bits[c]:
                verts = set(int(v) for v in member_vertex[c])
                if used & verts:
                    ok = False
                    break
                used |= verts
                val += w[c]
        if ok and val > best_val:
            best_val, best_sel = val, np.array(bits, bool)
    return best_sel, best_val


def random_instance(rng, n_cliques, k, n_vertices):
    mv = rng.integers(0, n_vertices, size=(n_cliques, k)).astype(np.int32)
    w = rng.uniform(0.01, 1.0, size=n_cliques).astype(np.float32)
    return mv, w


def test_parallel_equals_sequential_greedy(rng):
    for trial in range(20):
        mv, w = random_instance(rng, 60, 3, 40)
        valid = np.ones(60, bool)
        got = np.asarray(
            solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 40)
        )
        want = sequential_greedy(mv, w, valid)
        np.testing.assert_array_equal(got, want)


def test_greedy_with_ties(rng):
    # many duplicate weights force the index tie-break path
    mv, _ = random_instance(rng, 40, 3, 25)
    w = np.round(rng.uniform(0.1, 0.5, size=40), 1).astype(np.float32)
    valid = np.ones(40, bool)
    got = np.asarray(
        solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 25)
    )
    want = sequential_greedy(mv, w, valid)
    np.testing.assert_array_equal(got, want)


def test_greedy_respects_valid_mask(rng):
    mv, w = random_instance(rng, 30, 3, 20)
    valid = rng.random(30) < 0.5
    got = np.asarray(
        solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 20)
    )
    assert not np.any(got & ~valid)
    want = sequential_greedy(mv, w, valid)
    np.testing.assert_array_equal(got, want)


def test_packing_feasible(rng):
    mv, w = random_instance(rng, 100, 3, 50)
    valid = np.ones(100, bool)
    got = np.asarray(
        solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 50)
    )
    used = list(mv[got].reshape(-1))
    # a clique may repeat a vertex internally (random instance); check
    # across distinct cliques only
    per_clique = [set(int(v) for v in row) for row in mv[got]]
    for a, b in itertools.combinations(per_clique, 2):
        assert not (a & b)
    assert len(used) > 0


def test_exact_matches_brute_force(rng):
    for trial in range(10):
        mv, w = random_instance(rng, 12, 3, 10)
        got = solve_exact_py(mv, w.astype(np.float64))
        _, best_val = brute_force_exact(mv, w)
        np.testing.assert_allclose(w[got].sum(), best_val, rtol=1e-6)


def test_exact_beats_or_equals_greedy(rng):
    for trial in range(10):
        mv, w = random_instance(rng, 40, 3, 25)
        valid = np.ones(40, bool)
        g = np.asarray(
            solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 25)
        )
        e = solve_exact_py(mv, w.astype(np.float64))
        assert w[e].sum() >= w[g].sum() - 1e-6


def test_chain_adversarial():
    # chain A-B-C where greedy takes the middle (heaviest) but exact
    # takes the two ends
    mv = np.array([[0, 1, 2], [2, 3, 4], [4, 5, 6]], np.int32)
    w = np.array([0.6, 1.0, 0.6], np.float32)
    valid = np.ones(3, bool)
    g = np.asarray(solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 7))
    assert list(g) == [False, True, False]
    e = solve_exact_py(mv, w.astype(np.float64))
    assert list(e) == [True, False, True]
    assert np.isclose(w[e].sum(), 1.2)


def test_vmap_batched(rng):
    import jax

    mvs, ws = [], []
    for _ in range(4):
        mv, w = random_instance(rng, 30, 3, 20)
        mvs.append(mv)
        ws.append(w)
    mvs = jnp.asarray(np.stack(mvs))
    ws = jnp.asarray(np.stack(ws))
    valid = jnp.ones((4, 30), bool)
    batched = jax.vmap(lambda m, w, v: solve_greedy(m, w, v, 20))
    got = np.asarray(batched(mvs, ws, valid))
    for i in range(4):
        want = sequential_greedy(np.asarray(mvs[i]), np.asarray(ws[i]), np.ones(30, bool))
        np.testing.assert_array_equal(got[i], want)


def test_lp_rounding_never_worse_than_greedy(rng):
    from repic_tpu.ops.solver import solve_lp_rounding

    for trial in range(15):
        mv, w = random_instance(rng, 40, 3, 25)
        valid = rng.uniform(size=40) > 0.1
        g = np.asarray(
            solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 25)
        )
        lp = np.asarray(
            solve_lp_rounding(
                jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 25
            )
        )
        # feasible: no vertex shared between two selected cliques
        # (a random instance may repeat a vertex inside one clique;
        # real k-partite cliques cannot, so dedupe per clique)
        used = [
            v for c in np.where(lp)[0] for v in set(map(int, mv[c]))
        ]
        assert len(used) == len(set(used))
        assert not (lp & ~valid).any()
        assert w[lp].sum() >= w[g].sum() - 1e-6


def test_lp_rounding_beats_greedy_on_chain():
    """The adversarial chain where greedy is suboptimal: LP pricing
    recovers the exact optimum."""
    from repic_tpu.ops.solver import solve_lp_rounding

    mv = np.array([[0, 1, 2], [2, 3, 4], [4, 5, 6]], np.int32)
    w = np.array([0.6, 1.0, 0.6], np.float32)
    valid = np.ones(3, bool)
    lp = np.asarray(
        solve_lp_rounding(
            jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 7
        )
    )
    assert np.isclose(w[lp].sum(), 1.2)


def test_lp_rounding_close_to_exact(rng):
    """On adversarial random conflict soups (14 cliques over just 12
    vertices — far denser than real consensus problems), LP pricing
    must close part of the greedy-to-exact gap and stay within 10% of
    the optimum."""
    from repic_tpu.ops.solver import solve_exact_py, solve_lp_rounding

    total_lp = total_greedy = total_exact = 0.0
    for trial in range(10):
        mv, w = random_instance(rng, 14, 3, 12)
        lp = np.asarray(
            solve_lp_rounding(
                jnp.asarray(mv), jnp.asarray(w),
                jnp.ones(14, bool), 12,
            )
        )
        g = np.asarray(
            solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.ones(14, bool), 12)
        )
        e = solve_exact_py(mv, w.astype(np.float64))
        total_lp += w[lp].sum()
        total_greedy += w[g].sum()
        total_exact += w[e].sum()
    assert total_lp >= 0.90 * total_exact
    # never worse than greedy in aggregate (strict improvement is
    # seed-dependent; test_lp_rounding_beats_greedy_on_chain pins a
    # case where pricing strictly wins)
    assert total_lp >= total_greedy - 1e-6


def test_lp_rounding_beats_greedy_on_long_chain():
    """5-link conflict chain (VERDICT r1 item 3): greedy picks the two
    heavy middles (2.2); the optimum is the three light cliques (3.0).
    LP pricing must recover the optimum exactly."""
    from repic_tpu.ops.solver import solve_lp_rounding

    mv = np.array(
        [
            [0, 1, 2],
            [2, 3, 4],
            [4, 5, 6],
            [6, 7, 8],
            [8, 9, 10],
        ],
        np.int32,
    )
    w = np.array([1.0, 1.1, 1.0, 1.1, 1.0], np.float32)
    valid = np.ones(5, bool)
    g = np.asarray(
        solve_greedy(jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 11)
    )
    assert list(g) == [False, True, False, True, False]
    assert np.isclose(w[g].sum(), 2.2)
    e = solve_exact_py(mv, w.astype(np.float64))
    assert np.isclose(w[e].sum(), 3.0)
    lp = np.asarray(
        solve_lp_rounding(
            jnp.asarray(mv), jnp.asarray(w), jnp.asarray(valid), 11
        )
    )
    assert list(lp) == [True, False, True, False, True]
    assert np.isclose(w[lp].sum(), 3.0)
