"""On-device dual-decomposition solver (the ``lp_device`` rung):
feasibility property tests, padded-row inertness, vmap/jit parity,
ladder degradation on (injected) dual-ascent divergence, and the
directory pipeline's journaled host fallback — the ISSUE 18
acceptance surface for ``repic_tpu/solver/``.
"""

import numpy as np
import pytest

from repic_tpu.runtime import faults
from repic_tpu.runtime.ladder import solve_host_ladder
from repic_tpu.solver import (
    DEFAULT_NUM_ITERS,
    solve_dual_decomposition,
    solve_lp_device,
    solve_lp_device_host,
)


def _instance(rng, C=40, K=3, n=24):
    """A random packing instance with the pipeline's vid structure
    (vid = member + picker_column * capacity, so ids within one
    clique are always distinct)."""
    member = rng.integers(0, n, size=(C, K))
    vid = (member + np.arange(K)[None, :] * n).astype(np.int32)
    w = rng.uniform(0.1, 3.0, C).astype(np.float32)
    valid = rng.uniform(size=C) < 0.8
    return vid, w, valid, K * n


def _assert_feasible(vid, picked, valid):
    picked = np.asarray(picked)
    assert not np.any(picked & ~np.asarray(valid)), (
        "picked a padded/invalid clique"
    )
    used = np.asarray(vid)[picked].ravel()
    assert len(np.unique(used)) == used.size, (
        "a particle vertex appears in two picked cliques"
    )


# ---- feasibility / quality properties -------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_always_feasible_and_never_worse_than_greedy(seed):
    import jax.numpy as jnp

    from repic_tpu.ops.solver import solve_greedy

    rng = np.random.default_rng(seed)
    vid, w, valid, nv = _instance(rng)
    picked = np.asarray(solve_lp_device(
        jnp.asarray(vid), jnp.asarray(w), jnp.asarray(valid), nv
    ))
    _assert_feasible(vid, picked, valid)
    greedy = np.asarray(solve_greedy(
        jnp.asarray(vid), jnp.asarray(w), jnp.asarray(valid), nv
    ))
    assert w[picked].sum() >= w[greedy].sum() - 1e-5, (
        "lp_device fell below the greedy floor"
    )


def test_padded_rows_are_inert():
    """Appending invalid (padded) rows changes nothing: same picks on
    the real rows, padding never picked."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    vid, w, valid, nv = _instance(rng, C=20)
    base = np.asarray(solve_lp_device(
        jnp.asarray(vid), jnp.asarray(w), jnp.asarray(valid), nv
    ))
    pad = 12
    vid2 = np.concatenate([vid, np.zeros((pad, 3), np.int32)])
    w2 = np.concatenate([w, np.full(pad, 99.0, np.float32)])
    valid2 = np.concatenate([valid, np.zeros(pad, bool)])
    out = np.asarray(solve_lp_device(
        jnp.asarray(vid2), jnp.asarray(w2), jnp.asarray(valid2), nv
    ))
    assert not out[len(vid):].any(), "picked a padded row"
    np.testing.assert_array_equal(out[: len(vid)], base)


def test_empty_and_all_invalid_problems():
    import jax.numpy as jnp

    out = solve_dual_decomposition(
        jnp.zeros((4, 3), jnp.int32),
        jnp.zeros(4, jnp.float32),
        jnp.zeros(4, bool),
        12,
    )
    assert not np.asarray(out.picked).any()
    # an all-padding lane converges immediately, not at the budget
    assert int(out.iterations) < DEFAULT_NUM_ITERS
    assert bool(out.converged)


def test_stats_sane_on_easy_instance():
    """A conflict-free instance: every clique picked, zero gap."""
    import jax.numpy as jnp

    vid = jnp.arange(12, dtype=jnp.int32).reshape(4, 3)
    w = jnp.asarray([1.0, 2.0, 3.0, 4.0], jnp.float32)
    out = solve_dual_decomposition(
        vid, w, jnp.ones(4, bool), 12
    )
    assert np.asarray(out.picked).all()
    assert bool(out.converged)
    assert float(out.gap) < 1e-3
    assert int(out.iterations) <= DEFAULT_NUM_ITERS


# ---- jit / vmap parity ----------------------------------------------


def test_jit_and_eager_agree():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    vid, w, valid, nv = _instance(rng)
    args = (jnp.asarray(vid), jnp.asarray(w), jnp.asarray(valid))
    eager = solve_lp_device(*args, nv)
    jitted = jax.jit(
        solve_lp_device, static_argnums=(3,)
    )(*args, nv)
    np.testing.assert_array_equal(
        np.asarray(eager), np.asarray(jitted)
    )


def test_vmap_matches_per_instance_loop():
    """The batched (micrograph-axis) solve is bit-identical to
    solving each lane alone — the property the fused chunk program
    relies on."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    insts = [_instance(rng, C=24) for _ in range(5)]
    nv = insts[0][3]
    vids = jnp.asarray(np.stack([i[0] for i in insts]))
    ws = jnp.asarray(np.stack([i[1] for i in insts]))
    valids = jnp.asarray(np.stack([i[2] for i in insts]))
    batched = jax.vmap(
        lambda v, w, m: solve_lp_device(v, w, m, nv)
    )(vids, ws, valids)
    for i, (vid, w, valid, _) in enumerate(insts):
        solo = solve_lp_device(
            jnp.asarray(vid), jnp.asarray(w), jnp.asarray(valid), nv
        )
        np.testing.assert_array_equal(
            np.asarray(batched[i]), np.asarray(solo)
        )
        _assert_feasible(vid, np.asarray(batched[i]), valid)


# ---- ladder integration ---------------------------------------------

_MV = np.array([[0, 1], [1, 2], [2, 3], [3, 4]], np.int32)
_W = np.array([2.0, 1.5, 1.0, 0.4], np.float32)


@pytest.mark.faults
def test_ladder_lp_device_rung_runs_and_counts():
    from repic_tpu import telemetry

    solves = telemetry.counter("repic_solver_device_solves_total")
    before = solves.value()
    picked, used = solve_host_ladder(_MV, _W, 5, solver="lp_device")
    assert used == "lp_device"
    np.testing.assert_array_equal(
        picked, [True, False, True, False]
    )
    assert solves.value() == before + 1


@pytest.mark.faults
def test_injected_divergence_degrades_to_lp_then_greedy():
    with faults.fault_plan("solver_diverge:lp_device:inf"):
        picked, used = solve_host_ladder(
            _MV, _W, 5, solver="lp_device"
        )
    assert used == "lp"
    np.testing.assert_array_equal(
        picked, [True, False, True, False]
    )
    with faults.fault_plan(
        "solver_diverge:lp_device:inf", "solver_budget:lp:inf"
    ):
        picked, used = solve_host_ladder(
            _MV, _W, 5, solver="lp_device"
        )
    assert used == "greedy"


@pytest.mark.faults
def test_node_limit_fallback_surfaces_as_exact_fallback_rung():
    """Satellite 1: the silent per-component greedy fallback inside
    an unbudgeted exact solve now reports as its own rung instead of
    only bumping a process-wide counter."""
    mv = np.array([[i, i + 1] for i in range(30)], np.int32)
    w = np.linspace(1.0, 2.0, 30).astype(np.float32)
    picked, used = solve_host_ladder(
        mv, w, 31, solver="exact", node_limit=2
    )
    assert used == "exact_fallback"
    assert picked.any()  # the greedy fallback still packs
    # an unconstrained solve of the same instance stays exact
    _, used = solve_host_ladder(mv, w, 31, solver="exact")
    assert used == "exact"


def test_host_wrapper_emits_telemetry():
    from repic_tpu import telemetry

    iters = telemetry.counter(
        "repic_solver_device_iterations_total"
    )
    before = iters.value()
    picked, converged = solve_lp_device_host(_MV, _W, 5)
    assert converged
    assert iters.value() > before


# ---- directory pipeline: journaled divergence fallback --------------


def _make_dir(tmp_path, m=4, k=3, n=24, seed=0):
    rng = np.random.default_rng(seed)
    d = tmp_path / "picks"
    for p in range(k):
        (d / f"picker{p}").mkdir(parents=True)
    for i in range(m):
        base = rng.uniform(50, 950, size=(n, 2))
        for p in range(k):
            jit = rng.normal(0, 10, size=base.shape)
            conf = rng.uniform(0.1, 1.0, size=n)
            with open(d / f"picker{p}" / f"mic{i}.box", "wt") as f:
                for (x, y), c in zip(base + jit, conf):
                    f.write(
                        f"{x:.2f}\t{y:.2f}\t64\t64\t{c:.4f}\n"
                    )
    return str(d)


@pytest.mark.faults
def test_dir_run_journals_lp_device_rung_per_micrograph(tmp_path):
    from repic_tpu.pipeline.consensus import run_consensus_dir
    from repic_tpu.runtime.journal import read_journal

    data = _make_dir(tmp_path)
    out = str(tmp_path / "out")
    stats = run_consensus_dir(data, out, 64, use_mesh=False)
    assert sorted(stats["particle_counts"]) == [
        f"mic{i}" for i in range(4)
    ]
    latest = {
        e["name"]: e for e in read_journal(out) if "name" in e
    }
    for i in range(4):
        assert latest[f"mic{i}"]["solver"] == "lp_device"
        assert latest[f"mic{i}"]["status"] == "ok"


@pytest.mark.faults
def test_injected_divergence_journals_host_fallback(tmp_path):
    """``solver_diverge:mic1`` makes exactly that micrograph's device
    solve read as non-converged: it re-solves on the host ladder,
    its journal entry carries the fallback rung + degraded status +
    a ``solver_degraded`` event, and every other micrograph stays on
    ``lp_device`` — with outputs still written for all."""
    import os

    from repic_tpu.pipeline.consensus import run_consensus_dir
    from repic_tpu.runtime.journal import read_journal

    data = _make_dir(tmp_path)
    out = str(tmp_path / "out")
    with faults.fault_plan("solver_diverge:mic1:1"):
        stats = run_consensus_dir(data, out, 64, use_mesh=False)
        assert ("solver_diverge", "mic1") in faults.fired_log()
    assert sorted(stats["particle_counts"]) == [
        f"mic{i}" for i in range(4)
    ]
    for i in range(4):
        assert os.path.exists(os.path.join(out, f"mic{i}.box"))
    latest = {
        e["name"]: e for e in read_journal(out) if "name" in e
    }
    assert latest["mic1"]["solver"] in ("lp", "greedy")
    assert latest["mic1"]["status"] == "degraded"
    for i in (0, 2, 3):
        assert latest[f"mic{i}"]["solver"] == "lp_device"
        assert latest[f"mic{i}"]["status"] == "ok"
    events = [
        e for e in read_journal(out)
        if e.get("event") == "solver_degraded"
    ]
    assert len(events) == 1
    assert events[0]["micrograph"] == "mic1"
    assert events[0]["rung"] == "lp_device"
    assert events[0]["reason"] == "diverged"
