"""Solver optimality gates at stress density (VERDICT r4 item 2).

The example-scale gates (tests/test_golden_10017.py) prove >= 0.98
particle-set Jaccard vs the exact oracle on 12 real micrographs with
shallow conflicts.  These gates run the same comparison where packing
is hard: dense jittered fields at CI-feasible particle counts on the
stress code path (spatial bucketing + anchor-chunked assembly), in
three regimes —

* the standard stress density (configs[3] shape, scaled),
* a high-jitter variant whose ambiguous cross-picker matches create
  deep clique conflicts (the regime where greedy demonstrably leaves
  objective behind), and
* the k=5 mixed-box-size ensemble (configs[4] shape, scaled).

Full-scale (50k x 4) numbers are measured by bench_solver_quality.py
and recorded in docs/tpu.md (artifacts: SOLVER_QUALITY_r5.json /
SOLVER_QUALITY_r6.json — r6 adds the on-device dual-decomposition
``lp_device`` rung, gated here alongside greedy and lp).
"""

import numpy as np
import pytest

from bench_solver_quality import _mixed_synthesize
from bench_stress import synthesize
from repic_tpu.ops.solver import solve_exact
from repic_tpu.parallel.batching import PaddedBatch
from repic_tpu.pipeline.consensus import run_consensus_batch

N = 5000
GATE = 0.98


def _quality(batch, box, k, solver):
    """(min objective ratio, min particle Jaccard) vs exact across the
    batch's micrographs; asserts exact-solution feasibility inline."""
    res = run_consensus_batch(batch, box, use_mesh=False, solver=solver)
    ratios, jaccards = [], []
    for i in range(len(batch.names)):
        valid = np.asarray(res.valid[i])
        mem = np.asarray(res.member_idx[i])[valid]
        w = np.asarray(res.w[i])[valid].astype(np.float64)
        rep = np.asarray(res.rep_xy[i])[valid]
        picked = np.asarray(res.picked[i])[valid]
        vid = mem + np.arange(k)[None, :] * batch.capacity
        exact = solve_exact(vid, w)
        # feasibility of the exact reference solution itself
        used = vid[exact].ravel()
        assert len(used) == len(set(used.tolist()))
        obj, obj_exact = w[picked].sum(), w[exact].sum()
        assert obj <= obj_exact + 1e-6
        ratios.append(obj / obj_exact)
        a = {tuple(r) for r in rep[picked]}
        b = {tuple(r) for r in rep[exact]}
        jaccards.append(len(a & b) / len(a | b) if a | b else 1.0)
    return min(ratios), min(jaccards)


def _batch(xy, conf, mask, k):
    m = xy.shape[0]
    return PaddedBatch(
        xy=xy, conf=conf, mask=mask,
        names=tuple(f"m{i}" for i in range(m)),
        counts=np.full((m, k), xy.shape[2], np.int32),
    )


@pytest.mark.slow
@pytest.mark.parametrize("solver", ["greedy", "lp", "lp_device"])
@pytest.mark.parametrize(
    "workload,jitter",
    [("stress", 10.0), ("stress_hard", 40.0)],
)
def test_stress_density_within_gate_of_exact(workload, jitter, solver):
    xy, conf, mask = synthesize(1, 4, N, seed=11, jitter=jitter)
    ratio, jac = _quality(_batch(xy, conf, mask, 4), 180.0, 4, solver)
    assert ratio >= GATE, f"{workload}/{solver}: objective ratio {ratio}"
    assert jac >= GATE, f"{workload}/{solver}: particle Jaccard {jac}"


@pytest.mark.slow
@pytest.mark.parametrize("solver", ["greedy", "lp", "lp_device"])
def test_k5_mixed_within_gate_of_exact(solver):
    xy, conf, mask, sizes = _mixed_synthesize(1, 4000, seed=11)
    ratio, jac = _quality(
        _batch(xy, conf, mask, 5), sizes, 5, solver
    )
    assert ratio >= GATE, f"k5mixed/{solver}: objective ratio {ratio}"
    assert jac >= GATE, f"k5mixed/{solver}: particle Jaccard {jac}"
