"""Spatial bucketing tests: the bucketed clique enumeration must
reproduce the dense path exactly (same clique set, weights,
representatives) while never materializing O(N^2) IoU matrices, and
must remain complete under per-cell overflow escalation."""

import numpy as np
import pytest

import jax.numpy as jnp

from repic_tpu.ops.cliques import (
    enumerate_cliques,
    enumerate_cliques_bucketed,
)
from repic_tpu.ops.iou import pair_iou
from repic_tpu.ops.spatial import (
    bucket_particles,
    bucketed_neighbor_iou,
    grid_size,
)

BOX = 180.0


def _random_micrograph(rng, k=3, n=200, extent=4000.0, jitter=25.0):
    base = rng.uniform(0, extent - BOX, size=(n, 2))
    xy = np.stack(
        [base + rng.normal(0, jitter, size=base.shape) for _ in range(k)]
    ).astype(np.float32)
    conf = rng.uniform(0.05, 1.0, size=(k, n)).astype(np.float32)
    mask = np.ones((k, n), bool)
    # mask out a ragged tail per picker
    for p in range(k):
        mask[p, n - rng.integers(0, n // 4) :] = False
    return jnp.asarray(xy), jnp.asarray(conf), jnp.asarray(mask)


def _clique_key_set(cs):
    m = np.asarray(cs.member_idx)[np.asarray(cs.valid)]
    return {tuple(row) for row in m}


def test_bucket_table_complete():
    rng = np.random.default_rng(0)
    xy = jnp.asarray(rng.uniform(0, 2000, size=(300, 2)), jnp.float32)
    mask = jnp.asarray(rng.uniform(size=300) > 0.1)
    g = grid_size(2000 + BOX, BOX)
    bt = bucket_particles(xy, mask, BOX, grid=g, cell_capacity=64)
    assert int(bt.max_count) <= 64
    table = np.asarray(bt.table)
    listed = table[table < 300]
    # every unmasked particle appears exactly once
    assert sorted(listed) == sorted(np.where(np.asarray(mask))[0])


def test_bucketed_neighbor_iou_matches_dense():
    rng = np.random.default_rng(1)
    xa = jnp.asarray(rng.uniform(0, 1500, size=(128, 2)), jnp.float32)
    xb = xa + jnp.asarray(
        rng.normal(0, 40, size=(128, 2)), jnp.float32
    )
    ma = jnp.ones(128, bool)
    g = grid_size(1500 + BOX, BOX)
    bta = bucket_particles(xa, ma, BOX, grid=g, cell_capacity=32)
    btb = bucket_particles(xb, ma, BOX, grid=g, cell_capacity=32)
    iou_c, idx_c = bucketed_neighbor_iou(xa, ma, bta, xb, ma, btb, BOX)
    dense = np.asarray(pair_iou(xa, xb, BOX))
    iou_c, idx_c = np.asarray(iou_c), np.asarray(idx_c)
    # reconstruct a dense matrix from the candidate lists
    rebuilt = np.zeros_like(dense)
    for i in range(128):
        sel = idx_c[i] < 128
        rebuilt[i, idx_c[i][sel]] = iou_c[i][sel]
    # all positive-IoU entries must be recovered (prefilter complete)
    np.testing.assert_allclose(
        np.where(dense > 1e-6, dense, 0.0), rebuilt, atol=1e-6
    )


@pytest.mark.parametrize("k", [2, 3, 4])
def test_bucketed_cliques_match_dense(k):
    rng = np.random.default_rng(2 + k)
    xy, conf, mask = _random_micrograph(rng, k=k, n=160)
    g = grid_size(4000 + BOX, BOX)
    dense = enumerate_cliques(
        xy, conf, mask, BOX, max_neighbors=8
    )
    bucketed = enumerate_cliques_bucketed(
        xy, conf, mask, BOX, max_neighbors=8, grid=g, cell_capacity=32
    )
    assert int(bucketed.max_cell_count) <= 32
    assert _clique_key_set(dense) == _clique_key_set(bucketed)
    # weights agree clique-by-clique
    dw = {
        tuple(m): w
        for m, w, v in zip(
            np.asarray(dense.member_idx),
            np.asarray(dense.w),
            np.asarray(dense.valid),
        )
        if v
    }
    bw = {
        tuple(m): w
        for m, w, v in zip(
            np.asarray(bucketed.member_idx),
            np.asarray(bucketed.w),
            np.asarray(bucketed.valid),
        )
        if v
    }
    for key, w in dw.items():
        np.testing.assert_allclose(w, bw[key], rtol=1e-5)


def test_bucketed_overflow_detected():
    """Cramming many particles into one cell must be reported, not
    silently truncated."""
    rng = np.random.default_rng(9)
    n = 64
    xy = jnp.asarray(
        rng.uniform(0, 50, size=(2, n, 2)), jnp.float32
    )  # all in one box-size cell
    conf = jnp.ones((2, n), jnp.float32)
    mask = jnp.ones((2, n), bool)
    cs = enumerate_cliques_bucketed(
        xy, conf, mask, BOX, grid=8, cell_capacity=8
    )
    assert int(cs.max_cell_count) == n  # overflow visible to caller


def test_run_consensus_batch_spatial_matches_dense():
    from repic_tpu.parallel.batching import pad_batch
    from repic_tpu.pipeline.consensus import run_consensus_batch
    from repic_tpu.utils.box_io import BoxSet

    rng = np.random.default_rng(5)
    loaded = []
    for i in range(2):
        sets = []
        base = rng.uniform(0, 3800, size=(150, 2))
        for p in range(3):
            pts = base + rng.normal(0, 30, size=base.shape)
            sets.append(
                BoxSet(
                    xy=pts.astype(np.float32),
                    conf=rng.uniform(0.1, 1, 150).astype(np.float32),
                    wh=np.full((150, 2), BOX, np.float32),
                )
            )
        loaded.append((f"m{i}", sets))
    batch = pad_batch(loaded)
    dense = run_consensus_batch(
        batch, BOX, use_mesh=False, spatial=False
    )
    spatial = run_consensus_batch(
        batch, BOX, use_mesh=False, spatial=True
    )
    for i in range(2):
        dk = {
            tuple(m)
            for m, p in zip(
                np.asarray(dense.member_idx[i]),
                np.asarray(dense.picked[i]),
            )
            if p
        }
        sk = {
            tuple(m)
            for m, p in zip(
                np.asarray(spatial.member_idx[i]),
                np.asarray(spatial.picked[i]),
            )
            if p
        }
        assert dk == sk


def test_chunked_assembly_matches_dense():
    """Anchor-chunked, stream-compacted enumeration returns the same
    clique set as the dense path (ordering aside)."""
    rng = np.random.default_rng(11)
    xy, conf, mask = _random_micrograph(rng, k=3, n=128)
    g = grid_size(4000 + BOX, BOX)
    dense = enumerate_cliques(xy, conf, mask, BOX, max_neighbors=8)
    chunked = enumerate_cliques_bucketed(
        xy, conf, mask, BOX, max_neighbors=8, grid=g,
        cell_capacity=32, clique_capacity=512, anchor_chunk=16,
    )
    assert int(chunked.num_valid) == int(dense.num_valid)
    assert _clique_key_set(dense) == _clique_key_set(chunked)
    dw = {
        tuple(m): w
        for m, w, v in zip(
            np.asarray(dense.member_idx),
            np.asarray(dense.w),
            np.asarray(dense.valid),
        )
        if v
    }
    cw = {
        tuple(m): w
        for m, w, v in zip(
            np.asarray(chunked.member_idx),
            np.asarray(chunked.w),
            np.asarray(chunked.valid),
        )
        if v
    }
    assert dw.keys() == cw.keys()
    for key in dw:
        np.testing.assert_allclose(dw[key], cw[key], rtol=1e-5)


def test_chunked_assembly_non_divisible_anchor_count():
    """N not divisible by anchor_chunk must pad to chunk multiples
    (NOT collapse to one full-size block) and still match dense."""
    rng = np.random.default_rng(13)
    xy, conf, mask = _random_micrograph(rng, k=3, n=100)
    g = grid_size(4000 + BOX, BOX)
    dense = enumerate_cliques(xy, conf, mask, BOX, max_neighbors=8)
    chunked = enumerate_cliques_bucketed(
        xy, conf, mask, BOX, max_neighbors=8, grid=g,
        cell_capacity=32, clique_capacity=512, anchor_chunk=16,
    )
    assert int(chunked.num_valid) == int(dense.num_valid)
    assert _clique_key_set(dense) == _clique_key_set(chunked)


def test_bucketed_topk_non_divisible_chunk():
    """Anchor count not divisible by the streaming chunk size."""
    from repic_tpu.ops.spatial import bucketed_topk_neighbors

    rng = np.random.default_rng(14)
    n = 130
    xa = jnp.asarray(rng.uniform(0, 1500, size=(n, 2)), jnp.float32)
    xb = xa + jnp.asarray(rng.normal(0, 40, size=(n, 2)), jnp.float32)
    ma = jnp.ones(n, bool)
    g = grid_size(1500 + BOX, BOX)
    bta = bucket_particles(xa, ma, BOX, grid=g, cell_capacity=32)
    btb = bucket_particles(xb, ma, BOX, grid=g, cell_capacity=32)
    v1, i1, adj1 = bucketed_topk_neighbors(
        xa, ma, bta, xb, ma, btb, BOX, threshold=0.3, d=8, chunk=48
    )
    v2, i2, adj2 = bucketed_topk_neighbors(
        xa, ma, bta, xb, ma, btb, BOX, threshold=0.3, d=8, chunk=n
    )
    assert v1.shape == (n, 8)
    np.testing.assert_allclose(
        np.asarray(v1), np.asarray(v2), atol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(adj1), np.asarray(adj2))
    # indices may tie-permute within equal IoUs; compare value-sets
    for r in range(n):
        s1 = {
            (int(i), round(float(x), 5))
            for i, x in zip(np.asarray(i1[r]), np.asarray(v1[r]))
            if x > 0
        }
        s2 = {
            (int(i), round(float(x), 5))
            for i, x in zip(np.asarray(i2[r]), np.asarray(v2[r]))
            if x > 0
        }
        assert s1 == s2


def test_chunked_capacity_overflow_visible():
    """When clique_capacity is too small, num_valid still reports the
    true count so escalation triggers."""
    rng = np.random.default_rng(12)
    xy, conf, mask = _random_micrograph(rng, k=3, n=64)
    g = grid_size(4000 + BOX, BOX)
    full = enumerate_cliques_bucketed(
        xy, conf, mask, BOX, max_neighbors=8, grid=g,
        cell_capacity=32, clique_capacity=4096, anchor_chunk=16,
    )
    true_count = int(full.num_valid)
    assert true_count > 2
    tiny = enumerate_cliques_bucketed(
        xy, conf, mask, BOX, max_neighbors=8, grid=g,
        cell_capacity=32, clique_capacity=2, anchor_chunk=16,
    )
    assert int(tiny.num_valid) == true_count  # overflow not hidden
    assert int(np.asarray(tiny.valid).sum()) <= 2


def test_mixed_box_sizes_k5():
    """k=5 ensemble with per-picker box sizes: IoU uses
    inter/(sa^2+sb^2-inter) and the whole pipeline (dense and
    bucketed) agrees."""
    from repic_tpu.ops.iou import pair_iou_xy

    # closed form: corner boxes (0,0) size 100 and (10,10) size 140
    ov = min(0 + 100, 10 + 140) - max(0, 10)  # = 90
    inter = ov * ov
    expect = inter / (100.0**2 + 140.0**2 - inter)
    got = float(
        pair_iou_xy(
            jnp.float32(0), jnp.float32(0),
            jnp.float32(10), jnp.float32(10),
            100.0, 140.0,
        )
    )
    np.testing.assert_allclose(got, expect, rtol=1e-6)

    rng = np.random.default_rng(7)
    k = 5
    xy, conf, mask = _random_micrograph(rng, k=k, n=96, jitter=15.0)
    sizes = jnp.asarray([180.0, 160.0, 200.0, 180.0, 150.0])
    g = grid_size(4000 + 200, 200)
    dense = enumerate_cliques(xy, conf, mask, sizes, max_neighbors=4)
    bucketed = enumerate_cliques_bucketed(
        xy, conf, mask, sizes, max_neighbors=4, grid=g,
        cell_capacity=32,
    )
    assert int(dense.num_valid) > 0
    assert _clique_key_set(dense) == _clique_key_set(bucketed)


def test_mixed_box_sizes_batch_output(tmp_path):
    """End-to-end mixed-size consensus writes each row with its
    representative picker's box size."""
    from repic_tpu.parallel.batching import pad_batch
    from repic_tpu.pipeline.consensus import (
        run_consensus_batch,
        write_consensus_boxes,
    )
    from repic_tpu.utils.box_io import BoxSet

    rng = np.random.default_rng(8)
    sizes = np.asarray([180.0, 160.0, 200.0], np.float32)
    base = rng.uniform(0, 2000, size=(40, 2))
    sets = [
        BoxSet(
            xy=(base + rng.normal(0, 10, base.shape)).astype(np.float32),
            conf=rng.uniform(0.2, 1, 40).astype(np.float32),
            wh=np.full((40, 2), s, np.float32),
        )
        for s in sizes
    ]
    batch = pad_batch([("m0", sets)])
    res = run_consensus_batch(batch, sizes, use_mesh=False)
    assert int(np.asarray(res.picked).sum()) > 0
    write_consensus_boxes(batch, res, str(tmp_path), sizes)
    rows = (tmp_path / "m0.box").read_text().splitlines()
    assert rows
    written_sizes = {int(r.split("\t")[2]) for r in rows}
    assert written_sizes <= {180, 160, 200}
