"""In-process status server: /metrics, /status, /healthz.

The acceptance contract (ISSUE 7): live, well-formed data mid-run
when ``--status-port`` is set, and ZERO overhead — nothing bound,
spawned, or accumulated — when it is unset (the PR 3 disabled-mode
discipline).
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from repic_tpu.telemetry import server as tlm_server
from repic_tpu.telemetry.metrics import MetricsRegistry

# every non-comment exposition line: name{labels} value
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$"
)


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.headers, resp.read().decode()


@pytest.fixture
def server():
    reg = MetricsRegistry(enabled=True)
    reg.counter("repic_test_total", "test counter").inc(3, kind="a")
    reg.histogram("repic_test_seconds", "test histogram").observe(0.02)
    srv = tlm_server.StatusServer(port=0, registry=reg).start()
    try:
        yield srv
    finally:
        srv.stop()


def test_healthz(server):
    status, _, body = _get(server.port, "/healthz")
    assert status == 200
    assert body == "ok\n"


def test_metrics_is_well_formed_exposition(server):
    status, headers, body = _get(server.port, "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    assert "# TYPE repic_test_total counter" in body
    assert 'repic_test_total{kind="a"} 3' in body
    # histogram expansion: cumulative buckets + sum/count + +Inf
    assert 'repic_test_seconds_bucket{le="+Inf"} 1' in body
    assert "repic_test_seconds_count 1" in body
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"malformed line: {line!r}"


def test_metrics_is_live_not_a_snapshot(server):
    server.registry.counter("repic_test_total", "").inc(2, kind="a")
    _, _, body = _get(server.port, "/metrics")
    assert 'repic_test_total{kind="a"} 5' in body


def test_status_document_and_404(server):
    tlm_server.set_status(run_id="abc123", micrographs_total=7)
    status, headers, body = _get(server.port, "/status")
    assert status == 200
    assert headers["Content-Type"] == "application/json"
    doc = json.loads(body)
    assert doc["run_id"] == "abc123"
    assert doc["micrographs_total"] == 7
    assert doc["ts"] > 0
    try:
        _get(server.port, "/nope")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_status_includes_cluster_liveness(server, tmp_path):
    from repic_tpu.runtime.cluster import heartbeat_path

    coord = str(tmp_path)
    with open(heartbeat_path(coord, "h1"), "wt") as f:
        json.dump(
            {"host": "h1", "rank": 0, "seq": 1, "ts": time.time()}, f
        )
    tlm_server.set_status(
        cluster={"coordination_dir": coord, "host_timeout_s": 30.0}
    )
    _, _, body = _get(server.port, "/status")
    hosts = json.loads(body)["cluster"]["hosts"]
    assert hosts["h1"]["rung"] == "live"


def test_set_status_is_noop_without_server():
    assert tlm_server.active_server() is None
    tlm_server.set_status(run_id="should-vanish")
    assert tlm_server.get_status() == {}


def test_stop_clears_status_and_unbinds():
    srv = tlm_server.StatusServer(port=0).start()
    port = srv.port
    tlm_server.set_status(run_id="x")
    srv.stop()
    assert tlm_server.active_server() is None
    assert tlm_server.get_status() == {}
    with pytest.raises(OSError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=1
        )


def test_maybe_status_server_none_is_inert():
    with tlm_server.maybe_status_server(None) as srv:
        assert srv is None
        assert tlm_server.active_server() is None


def test_mid_run_scrape(tmp_path):
    """The CI acceptance scenario in-process: scrape /status and
    /metrics while a real consensus run executes."""
    from repic_tpu.pipeline.consensus import run_consensus_dir

    rng = np.random.default_rng(3)
    data = tmp_path / "picks"
    for p in range(3):
        (data / f"picker{p}").mkdir(parents=True)
    for i in range(4):
        base = rng.uniform(50, 950, size=(20, 2))
        for p in range(3):
            xy = base + rng.normal(0, 5, size=base.shape)
            with open(
                data / f"picker{p}" / f"mic{i}.box", "wt"
            ) as f:
                for (x, y) in xy:
                    f.write(f"{x:.2f}\t{y:.2f}\t64\t64\t0.5\n")

    with tlm_server.maybe_status_server(0) as srv:
        done = threading.Event()
        errors = []

        def _run():
            try:
                run_consensus_dir(
                    str(data), str(tmp_path / "out"), 64,
                    use_mesh=False,
                )
            except Exception as e:  # pragma: no cover
                errors.append(e)
            finally:
                done.set()

        t = threading.Thread(target=_run)
        t.start()
        # scrape while the run is (most likely) still live; the
        # assertions hold either way — the server outlives the run
        seen_total = None
        while not done.is_set():
            _, _, body = _get(srv.port, "/status")
            doc = json.loads(body)
            if doc.get("micrographs_total"):
                seen_total = doc["micrographs_total"]
                break
            time.sleep(0.01)
        done.wait(timeout=120)
        t.join(timeout=120)
        assert not errors, errors
        # final scrape: complete progress + live registry
        _, _, body = _get(srv.port, "/status")
        doc = json.loads(body)
        assert doc["micrographs_total"] == 4
        assert doc.get("run_id")
        if seen_total is not None:
            assert seen_total == 4
        _, _, metrics_body = _get(srv.port, "/metrics")
        assert "repic_consensus_micrographs_total" in metrics_body


def test_resumed_run_status_counts_prior_work(tmp_path):
    """Regression: /status progress covers the WHOLE run — a resumed
    generation counts the already-done micrographs, not just its own
    share (a 90%-done resume must not read as 10%)."""
    from repic_tpu.pipeline.consensus import run_consensus_dir

    rng = np.random.default_rng(7)
    data = tmp_path / "picks"
    for p in range(3):
        (data / f"picker{p}").mkdir(parents=True)
    for i in range(4):
        base = rng.uniform(50, 950, size=(15, 2))
        for p in range(3):
            xy = base + rng.normal(0, 5, size=base.shape)
            with open(
                data / f"picker{p}" / f"mic{i}.box", "wt"
            ) as f:
                for (x, y) in xy:
                    f.write(f"{x:.2f}\t{y:.2f}\t64\t64\t0.5\n")
    out = str(tmp_path / "out")
    run_consensus_dir(str(data), out, 64, use_mesh=False)
    # drop one output + journal entry so the resume has real work
    os.remove(os.path.join(out, "mic3.box"))
    with tlm_server.maybe_status_server(0) as srv:
        run_consensus_dir(
            str(data), out, 64, use_mesh=False, resume=True
        )
        _, _, body = _get(srv.port, "/status")
        doc = json.loads(body)
    assert doc["micrographs_total"] == 4
    assert doc["micrographs_done"] == 4, doc
