"""Scaled-down stress-config test (BASELINE configs[3] shape).

The real config is 50k particles x 4 pickers x 128 micrographs
(exercised on hardware by bench_stress.py; results in docs/tpu.md).
Here the same code path — auto-selected spatial bucketing, capacity
probe, anchor-chunked assembly — runs at 5k particles on the CPU mesh
and is validated against the dense all-pairs path.
"""

import numpy as np
import pytest

from repic_tpu.parallel.batching import PaddedBatch
from repic_tpu.pipeline.consensus import (
    SPATIAL_THRESHOLD,
    run_consensus_batch,
)

N = 5000
K = 4
BOX = 180.0


@pytest.fixture(scope="module")
def stress_batch():
    assert N > SPATIAL_THRESHOLD  # auto-selects the bucketed path
    rng = np.random.default_rng(33)
    side = int(np.ceil(np.sqrt(N)))
    gx, gy = np.meshgrid(np.arange(side), np.arange(side))
    base = (
        np.stack([gx, gy], -1).reshape(-1, 2)[:N].astype(np.float32)
        * 150.0
        + 150.0
    )
    xy = np.stack(
        [
            base + rng.normal(0, 10, base.shape).astype(np.float32)
            for _ in range(K)
        ]
    )[None]
    conf = rng.uniform(0.05, 1.0, size=(1, K, N)).astype(np.float32)
    mask = np.ones((1, K, N), bool)
    return PaddedBatch(
        xy=xy,
        conf=conf,
        mask=mask,
        names=("m0",),
        counts=np.full((1, K), N, np.int32),
    )


@pytest.mark.slow
def test_auto_spatial_matches_dense_at_stress_scale(stress_batch):
    auto = run_consensus_batch(stress_batch, BOX, use_mesh=False)
    dense = run_consensus_batch(
        stress_batch, BOX, use_mesh=False, spatial=False
    )
    assert int(np.asarray(auto.num_cliques).sum()) == int(
        np.asarray(dense.num_cliques).sum()
    )
    ak = {
        tuple(m)
        for m, p in zip(
            np.asarray(auto.member_idx[0]), np.asarray(auto.picked[0])
        )
        if p
    }
    dk = {
        tuple(m)
        for m, p in zip(
            np.asarray(dense.member_idx[0]), np.asarray(dense.picked[0])
        )
        if p
    }
    assert ak == dk
    assert len(ak) > 0.9 * N  # nearly every true particle recovered


@pytest.mark.slow
def test_stress_feasibility_and_counts(stress_batch):
    res = run_consensus_batch(stress_batch, BOX, use_mesh=False)
    picked = np.asarray(res.picked[0])
    mem = np.asarray(res.member_idx[0])[picked]
    used = [(p, int(row[p])) for row in mem for p in range(K)]
    assert len(used) == len(set(used))  # no particle reused


def test_repeat_batches_reuse_probed_config():
    """Repeat same-shape batches must reuse the first call's probed
    capacity config (one jit entry), not re-anchor to the default
    max_neighbors and compile a second, larger program."""
    import repic_tpu.pipeline.consensus as C

    rng = np.random.default_rng(44)
    base = rng.uniform(0, 3000, size=(120, 2)).astype(np.float32)
    xy = np.stack(
        [base + rng.normal(0, 15, base.shape).astype(np.float32)
         for _ in range(3)]
    )[None]
    conf = rng.uniform(0.1, 1, size=(1, 3, 120)).astype(np.float32)
    mask = np.ones((1, 3, 120), bool)
    batch = PaddedBatch(
        xy=xy, conf=conf, mask=mask, names=("m0",),
        counts=np.full((1, 3), 120, np.int32),
    )
    key = (xy.shape, (180.0,), 0.3, False)
    C._LAST_GOOD_CONFIG.pop(key, None)
    C.run_consensus_batch(batch, 180.0, use_mesh=False)
    first = C._LAST_GOOD_CONFIG[key]
    size_after_first = C._make_batched_consensus.cache_info().currsize
    C.run_consensus_batch(batch, 180.0, use_mesh=False)
    assert C._LAST_GOOD_CONFIG[key] == first  # config stable
    assert (
        C._make_batched_consensus.cache_info().currsize
        == size_after_first
    )  # no second program compiled for the same shape


def test_outlier_chunk_does_not_promote_base_config():
    """One dense outlier chunk must not double every later chunk's
    program: the recorded config tracks the TYPICAL chunk (lower
    median of the last three requirement tuples) — an isolated
    outlier escalates locally without promoting it, two of the last
    three chunks being large promotes it, and it demotes again once
    dense chunks stop arriving (the pre-policy behavior cost a
    measured 1.8x on the 1024-directory workload)."""
    import repic_tpu.pipeline.consensus as C

    rng = np.random.default_rng(7)
    n = 48

    def batch(dense):
        if dense:
            # one tight cluster: adjacency ~ n, far above the base
            base_xy = rng.uniform(500, 560, size=(n, 2))
        else:
            # spread grid: adjacency ~ 1
            gx, gy = np.meshgrid(np.arange(8), np.arange(6))
            base_xy = (
                np.stack([gx, gy], -1).reshape(-1, 2)[:n] * 400.0
                + 200.0
            )
        xy = np.stack(
            [
                base_xy + rng.normal(0, 5, base_xy.shape)
                for _ in range(2)
            ]
        )[None].astype(np.float32)
        conf = rng.uniform(0.1, 1, size=(1, 2, n)).astype(np.float32)
        return PaddedBatch(
            xy=xy,
            conf=conf,
            mask=np.ones((1, 2, n), bool),
            names=("m0",),
            counts=np.full((1, 2), n, np.int32),
        )

    key = ((1, 2, n, 2), (180.0,), 0.3, False)
    C._LAST_GOOD_CONFIG.pop(key, None)
    C._RECENT_REQUIREMENTS.pop(key, None)

    C.run_consensus_batch(batch(False), 180.0, use_mesh=False)
    base_cfg = C._LAST_GOOD_CONFIG[key]

    res = C.run_consensus_batch(batch(True), 180.0, use_mesh=False)
    assert int(np.asarray(res.num_cliques)) > 0  # outlier still solved
    assert C._LAST_GOOD_CONFIG[key] == base_cfg  # base not promoted

    C.run_consensus_batch(batch(False), 180.0, use_mesh=False)
    assert C._LAST_GOOD_CONFIG[key] == base_cfg  # still the base

    C.run_consensus_batch(batch(True), 180.0, use_mesh=False)
    C.run_consensus_batch(batch(True), 180.0, use_mesh=False)
    promoted = C._LAST_GOOD_CONFIG[key]
    assert promoted[0] > base_cfg[0]  # consecutive outliers promote

    C.run_consensus_batch(batch(False), 180.0, use_mesh=False)
    C.run_consensus_batch(batch(False), 180.0, use_mesh=False)
    # dense chunks stopped arriving: the config demotes again
    assert C._LAST_GOOD_CONFIG[key][0] == base_cfg[0]


def test_packed_probe_escalation_matches_default():
    """The packed-probe path (one fused transfer carrying probes AND
    writer outputs) must survive a forced escalation retry: record a
    sparse batch's small config, then feed a dense same-shape batch —
    the packed head-row probes drive the retry, and the final result
    equals the default (separate-probe-fetch) path exactly."""
    import repic_tpu.pipeline.consensus as C

    rng = np.random.default_rng(11)
    n = 96

    def make(dense):
        if dense:
            base = rng.uniform(700, 760, size=(n, 2)).astype(np.float32)
        else:
            gx, gy = np.meshgrid(np.arange(12), np.arange(8))
            base = (
                np.stack([gx, gy], -1).reshape(-1, 2)[:n] * 400.0
            ).astype(np.float32)
        xy = np.stack(
            [base + rng.normal(0, 10, base.shape).astype(np.float32)
             for _ in range(3)]
        )[None]
        conf = rng.uniform(0.1, 1, size=(1, 3, n)).astype(np.float32)
        mask = np.ones((1, 3, n), bool)
        return PaddedBatch(
            xy=xy, conf=conf, mask=mask, names=("m0",),
            counts=np.full((1, 3), n, np.int32),
        )

    sparse, dense = make(False), make(True)
    key = (sparse.xy.shape, (180.0,), 0.3, False)
    C._LAST_GOOD_CONFIG.pop(key, None)
    C._RECENT_REQUIREMENTS.pop(key, None)
    # seed a small config from the sparse batch (packed mode too)
    _, _ = C.run_consensus_batch(
        sparse, 180.0, use_mesh=False, packed_probe=True
    )
    small = C._LAST_GOOD_CONFIG[key]
    # dense same-shape batch must escalate within packed mode (the
    # lower-median record policy keeps the RECORDED config at the
    # sparse value — the retry is local): the packed head-row probes
    # prove the dense requirement exceeded the seeded capacity
    res_p, packed = C.run_consensus_batch(
        dense, 180.0, use_mesh=False, packed_probe=True
    )
    assert C._packed_probes(packed).max(axis=0)[0] > small[0]
    # the packed encoding must mirror the live result it rode with
    picked_p, rep_p, _conf_p, _slot_p, nc_p = C._unpack_box_outputs(
        packed
    )
    np.testing.assert_array_equal(
        picked_p, np.asarray(res_p.picked & res_p.valid)
    )
    # ...and agree exactly with the default path on the same data
    C._LAST_GOOD_CONFIG.pop(key, None)
    C._RECENT_REQUIREMENTS.pop(key, None)
    res_d = C.run_consensus_batch(dense, 180.0, use_mesh=False)
    sel_p = np.where(picked_p[0])[0]
    sel_d = np.where(np.asarray(res_d.picked[0]))[0]

    def rows_sorted(a):
        # sort whole (x, y) ROWS so differing point sets cannot
        # false-pass a column-independent sort
        return a[np.lexsort((a[:, 1], a[:, 0]))]

    np.testing.assert_array_equal(
        rows_sorted(rep_p[0][sel_p]),
        rows_sorted(np.asarray(res_d.rep_xy[0])[sel_d]),
    )
    assert int(nc_p[0]) == int(np.asarray(res_d.num_cliques[0]))
