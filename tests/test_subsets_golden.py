"""Split-membership golden test vs the EXECUTED reference splitter.

The defocus-stratified train/val/test split must be seed-identical to
the reference (same rng stream, same tertile binning, same round-robin
sampling) because iterative-picking results depend on exactly which
micrographs land in each subset.  Here the reference
build_subsets.py is executed in-process on a synthetic 24-micrograph
defocus table and the resulting symlink trees are compared one-to-one
with ours.
"""

import os
import runpy
import sys
from types import SimpleNamespace

import numpy as np
import pytest

REF_UTILS = "/root/reference/repic/utils"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_UTILS), reason="reference not mounted"
)


def _make_inputs(root, n=24, seed=3):
    from repic_tpu.utils import mrc as mrc_io

    rng = np.random.default_rng(seed)
    mrc_dir = root / "mrc"
    box_dir = root / "box"
    mrc_dir.mkdir()
    box_dir.mkdir()
    lines = []
    for i in range(n):
        stem = f"mic_{i:02d}"
        mrc_io.write_mrc(
            str(mrc_dir / f"{stem}.mrc"),
            np.zeros((4, 4), np.float32),
        )
        (box_dir / f"{stem}.box").write_text("10\t10\t100\t100\t0.5\n")
        dx, dy = rng.uniform(8000, 30000, 2)
        lines.append(f"{stem}.mrc\t{dx:.2f}\t{dy:.2f}")
    defocus = root / "defocus.txt"
    defocus.write_text("\n".join(lines) + "\n")
    return defocus, box_dir, mrc_dir


def _tree(out_dir):
    """{subdir: frozenset(mrc stems)} of a split tree."""
    out = {}
    for dirpath, _, files in os.walk(out_dir):
        stems = {
            f[:-4] for f in files if f.endswith(".mrc")
        }
        if stems:
            rel = os.path.relpath(dirpath, out_dir)
            out[rel] = frozenset(stems)
    return out


def _run_reference(defocus, box_dir, mrc_dir, out_dir):
    """Execute the reference build_subsets.main in-process."""
    sys.path.insert(0, REF_UTILS)
    try:
        import matplotlib

        matplotlib.use("Agg")
        # mrcfile is not installed in this image; stub it with a
        # reader that accepts any of the synthetic files as a valid
        # single-frame micrograph
        import types
        from contextlib import contextmanager

        stub = types.ModuleType("mrcfile")

        @contextmanager
        def _open(path, permissive=True):
            yield SimpleNamespace(data=np.zeros((4, 4), np.float32))

        stub.open = _open
        sys.modules["mrcfile"] = stub
        ref_mod = runpy.run_path(
            os.path.join(REF_UTILS, "build_subsets.py"),
            run_name="ref_build_subsets",
        )
        # The reference enumerates micrographs with unsorted
        # glob.glob, so its split membership depends on filesystem
        # hash order.  Pin the order to sorted (matching our
        # deterministic scan) so this test compares the ALGORITHM,
        # not ext4 enumeration.
        import glob as _glob

        fake_glob = types.ModuleType("glob")
        fake_glob.glob = lambda p: sorted(_glob.glob(p))
        ref_mod["main"].__globals__["glob"] = fake_glob
        args = SimpleNamespace(
            defocus_file=str(defocus),
            box_dir=str(box_dir),
            mrc_dir=str(mrc_dir),
            out_dir=str(out_dir),
            train_set=None,
            ignore_test=False,
        )
        ref_mod["main"](args)
    finally:
        sys.path.remove(REF_UTILS)


def test_split_membership_matches_reference_equal_weight_path(tmp_path):
    """The reference's only *executable* mode.

    Reference bug worth knowing: build_subsets.main reads the
    module-global ``use_defocus_values`` but also assigns it in the
    file-missing branch, making it function-local — so main() raises
    UnboundLocalError whenever the defocus file EXISTS, and the
    equal-weight MRC-scan branch is the only one that ever runs.
    This test executes that branch unmodified and asserts identical
    split membership from our splitter in the same mode."""
    defocus, box_dir, mrc_dir = _make_inputs(tmp_path)
    missing = str(defocus) + ".nope"
    ref_out = tmp_path / "ref_out"
    _run_reference(missing, box_dir, mrc_dir, ref_out)

    from repic_tpu.utils import subsets

    ours_out = tmp_path / "ours_out"
    subsets.main(
        SimpleNamespace(
            defocus_file=missing,
            box_dir=str(box_dir),
            mrc_dir=str(mrc_dir),
            out_dir=str(ours_out),
            train_set=None,
            ignore_test=False,
            seed=0,
        )
    )

    ref_tree = _tree(ref_out)
    our_tree = _tree(ours_out)
    assert ref_tree.keys() == our_tree.keys()
    for sub in ref_tree:
        assert our_tree[sub] == ref_tree[sub], f"{sub} differs"
    # sanity on the reference shape itself
    assert any(s.startswith("val") for s in ref_tree)
    assert any("train" in s for s in ref_tree)


def test_reference_defocus_branch_is_dead_code(tmp_path):
    """Pin the reference bug: with an existing defocus file, the
    reference main() crashes with UnboundLocalError (use_defocus_values
    becomes function-local).  Our splitter implements the documented
    intent instead; if a reference release ever fixes this, this test
    will flag that the golden coverage should be extended."""
    defocus, box_dir, mrc_dir = _make_inputs(tmp_path)
    with pytest.raises(UnboundLocalError):
        _run_reference(defocus, box_dir, mrc_dir, tmp_path / "ref_out")
