"""Supplementary-data parity (VERDICT r4 item 6).

The reference's three supplementary files (reference README.md:56,
supp_data_files/) are committed verbatim under
``supp_data/reference_files/``; the two ODS spreadsheets additionally
ship greppable TSV extractions.  These gates keep the committed bytes
honest against the mounted reference and the extractions reproducible
from the committed ODS.
"""

import filecmp
import os

import pytest

HERE = os.path.dirname(__file__)
SUPP = os.path.join(
    os.path.dirname(HERE), "supp_data", "reference_files"
)
REF = "/root/reference/supp_data_files"

FILES = [
    "supplemental_data_file_1.txt",
    "supplemental_data_file_2.ods",
    "supplemental_data_file_3.ods",
]


@pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference supp data not mounted"
)
@pytest.mark.parametrize("name", FILES)
def test_committed_files_match_reference_bytes(name):
    assert filecmp.cmp(
        os.path.join(SUPP, name), os.path.join(REF, name), shallow=False
    ), name


def test_micrograph_list_shape():
    lines = open(
        os.path.join(SUPP, "supplemental_data_file_1.txt")
    ).read().splitlines()
    assert len(lines) == 460
    assert all(ln.endswith(".mrc") for ln in lines)


def test_tsv_extractions_reproduce(tmp_path):
    import shutil
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(HERE), "supp_data")
    )
    try:
        import extract_ods
    finally:
        sys.path.pop(0)
    for n in (2, 3):
        ods = f"supplemental_data_file_{n}.ods"
        tsv = f"supplemental_data_file_{n}_sheet_Sheet1.tsv"
        shutil.copy(os.path.join(SUPP, ods), tmp_path / ods)
        written = extract_ods.extract(str(tmp_path / ods))
        assert [os.path.basename(w) for w in written] == [tsv]
        assert (
            (tmp_path / tsv).read_text(encoding="utf-8")
            == open(
                os.path.join(SUPP, tsv), encoding="utf-8"
            ).read()
        ), tsv


def test_parameter_tsv_has_empiar_10017_column():
    """The extraction is content-bearing, not an empty grid: the
    parameter sheet must carry the EMPIAR sets the paper covers."""
    text = open(
        os.path.join(
            SUPP, "supplemental_data_file_2_sheet_Sheet1.tsv"
        ),
        encoding="utf-8",
    ).read()
    for token in ("10005", "10017", "10057", "10454", "Box size"):
        assert token in text, token
    # merged-cell alignment: the defocus triple belongs to the LAST
    # dataset column (EMPIAR-10454), which a covered-cell-skipping
    # extractor would shift one column left
    row = next(
        ln for ln in text.splitlines() if "Defocus" in ln
    ).split("\t")
    assert row[-1].startswith("(5000"), row
    assert len(row) == 5, row
