"""Telemetry package: registry semantics, spans, sinks, probes.

Everything runs under JAX_PLATFORMS=cpu (conftest) — the probe layer
must degrade gracefully there, which is itself under test.
"""

import json
import time

import pytest

from repic_tpu.telemetry import events as tlm_events
from repic_tpu.telemetry import probes, sinks
from repic_tpu.telemetry.metrics import MetricsRegistry

# ---------------------------------------------------------------- #
# metrics registry                                                 #
# ---------------------------------------------------------------- #


def test_counter_inc_and_labels():
    reg = MetricsRegistry(enabled=True)
    c = reg.counter("c_total", "help text")
    c.inc()
    c.inc(2.5)
    c.inc(rung="exact")
    c.inc(3, rung="exact")
    assert c.value() == 3.5
    assert c.value(rung="exact") == 4.0
    assert c.value(rung="lp") == 0.0


def test_counter_rejects_decrease():
    reg = MetricsRegistry(enabled=True)
    with pytest.raises(ValueError):
        reg.counter("c_total").inc(-1)


def test_get_or_create_returns_same_handle():
    reg = MetricsRegistry(enabled=True)
    assert reg.counter("x_total") is reg.counter("x_total")


def test_kind_conflict_raises():
    reg = MetricsRegistry(enabled=True)
    reg.counter("x_total")
    with pytest.raises(ValueError):
        reg.gauge("x_total")


def test_gauge_set_add():
    reg = MetricsRegistry(enabled=True)
    g = reg.gauge("g")
    g.set(4.0, host="a")
    g.add(1.5, host="a")
    g.set(7.0, host="b")
    assert g.value(host="a") == 5.5
    assert g.value(host="b") == 7.0


def test_histogram_buckets_sum_count():
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    # disjoint per-bucket counts: <=0.1, <=1, <=10, +Inf
    assert snap["counts"] == [1, 2, 1, 1]
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(56.05)


def test_disabled_registry_is_noop():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("c_total")
    h = reg.histogram("h_seconds")
    c.inc()
    h.observe(1.0)
    reg.gauge("g").set(5)
    assert c.value() == 0.0
    assert h.snapshot() is None
    assert all(
        not inst.samples() for inst in reg.instruments()
    )


def test_as_dict_shape():
    reg = MetricsRegistry(enabled=True)
    reg.counter("c_total", "a counter").inc(2, k="v")
    reg.histogram("h_seconds", buckets=(1.0,)).observe(0.5)
    d = reg.as_dict()
    assert d["c_total"]["kind"] == "counter"
    assert d["c_total"]["samples"] == [
        {"labels": {"k": "v"}, "value": 2.0}
    ]
    assert d["h_seconds"]["bucket_edges"] == [1.0]
    assert d["h_seconds"]["samples"][0]["count"] == 1


def test_disabled_mode_overhead_smoke():
    """The disabled fast path must be branch-cheap: 20k no-op
    increments + 20k no-op spans in well under a second (generous
    bound — the point is catching an accidentally-hot disabled
    path, not micro-benchmarking)."""
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("c_total")
    t0 = time.perf_counter()
    for _ in range(20_000):
        c.inc()
    saved = tlm_events.metrics.REGISTRY._enabled
    tlm_events.metrics.REGISTRY._enabled = False
    try:
        for _ in range(20_000):
            with tlm_events.span("noop"):
                pass
    finally:
        tlm_events.metrics.REGISTRY._enabled = saved
    assert time.perf_counter() - t0 < 1.0


# ---------------------------------------------------------------- #
# events: spans, run log, logger                                   #
# ---------------------------------------------------------------- #


def _with_log(tmp_path, fn):
    path = str(tmp_path / "events.jsonl")
    log = tlm_events.EventLog(path)
    prev = tlm_events.set_current_log(log)
    try:
        fn()
    finally:
        tlm_events.set_current_log(prev)
        log.close()
    return tlm_events.read_events(path), log.run_id


def test_span_nesting_parent_ids(tmp_path):
    def work():
        with tlm_events.span("outer", micrographs=2):
            with tlm_events.span("inner"):
                pass
            with tlm_events.span("inner"):
                pass

    records, run_id = _with_log(tmp_path, work)
    spans = [r for r in records if r["ev"] == "span"]
    # children close before the parent -> two inners then one outer
    assert [s["name"] for s in spans] == ["inner", "inner", "outer"]
    outer = spans[2]
    assert outer["micrographs"] == 2
    assert "parent" not in outer
    assert all(s["parent"] == outer["span"] for s in spans[:2])
    assert {s["run"] for s in spans} == {run_id}
    assert len({s["span"] for s in spans}) == 3


def test_span_records_error_and_reraises(tmp_path):
    def work():
        with pytest.raises(ValueError):
            with tlm_events.span("fails"):
                raise ValueError("boom")

    records, _ = _with_log(tmp_path, work)
    (span,) = [r for r in records if r["ev"] == "span"]
    assert span["error"] == "ValueError"


def test_event_and_logger_records(tmp_path, capsys):
    def work():
        tlm_events.event("capacity_escalated", cap=2048)
        tlm_events.get_logger("consensus").info(
            "chunk retried", attempt=2
        )

    records, _ = _with_log(tmp_path, work)
    (ev,) = [r for r in records if r["ev"] == "event"]
    assert ev["name"] == "capacity_escalated" and ev["cap"] == 2048
    (lg,) = [r for r in records if r["ev"] == "log"]
    assert lg["level"] == "info" and lg["attempt"] == 2
    out = capsys.readouterr().out
    # greppable: original message text intact behind the prefix
    assert "chunk retried" in out
    assert "repic-tpu INFO [consensus]" in out
    assert "attempt=2" in out


def test_logger_level_threshold(capsys, monkeypatch):
    monkeypatch.setenv("REPIC_TPU_LOG_LEVEL", "warning")
    log = tlm_events.get_logger("t")
    log.info("hidden")
    log.warning("shown")
    captured = capsys.readouterr()
    assert "hidden" not in captured.out + captured.err
    assert "shown" in captured.err


def test_spans_noop_without_run_log(tmp_path):
    # no current log: spans still run the body, write nothing
    with tlm_events.span("lonely"):
        pass
    assert tlm_events.read_events(str(tmp_path)) == []


# ---------------------------------------------------------------- #
# sinks                                                            #
# ---------------------------------------------------------------- #


def _sample_registry():
    reg = MetricsRegistry(enabled=True)
    reg.counter("repic_c_total", "a counter").inc(3, kind="x")
    reg.gauge("repic_g", "a gauge").set(1.5)
    h = reg.histogram(
        "repic_h_seconds", "a histogram", buckets=(0.1, 1.0)
    )
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    return reg


def test_metrics_json_roundtrip(tmp_path):
    reg = _sample_registry()
    path = str(tmp_path / "_metrics.json")
    sinks.write_metrics_json(path, reg)
    metrics = sinks.read_metrics_json(path)
    assert metrics == reg.as_dict()
    # directory form resolves the default name
    assert sinks.read_metrics_json(str(tmp_path)) == metrics


def test_prometheus_textfile(tmp_path):
    reg = _sample_registry()
    path = str(tmp_path / "_metrics.prom")
    sinks.write_prometheus_textfile(path, reg)
    text = open(path).read()
    assert '# TYPE repic_c_total counter' in text
    assert 'repic_c_total{kind="x"} 3' in text
    assert "repic_g 1.5" in text
    # cumulative buckets: 1, 2, then +Inf == count == 3
    assert 'repic_h_seconds_bucket{le="0.1"} 1' in text
    assert 'repic_h_seconds_bucket{le="1"} 2' in text
    assert 'repic_h_seconds_bucket{le="+Inf"} 3' in text
    assert "repic_h_seconds_count 3" in text


def test_runtime_tsv_shape(tmp_path):
    path = sinks.write_runtime_tsv(
        str(tmp_path), [("load", 0.5), ("load", 0.25)]
    )
    assert open(path).read() == "load\t0.500000\nload\t0.250000\n"


# ---------------------------------------------------------------- #
# probes                                                           #
# ---------------------------------------------------------------- #


def test_record_transfer_accumulates():
    c0 = probes.counters()
    probes.record_transfer(1024)
    probes.record_transfer(512, fetches=2)
    c1 = probes.counters()
    assert c1[1] - c0[1] == 1536
    assert c1[2] - c0[2] == 3


def test_recompile_listener_counts_fresh_compile():
    import jax
    import jax.numpy as jnp
    import numpy as np

    assert probes.install()
    before = probes.counters()[0]
    # unique embedded constant -> guaranteed fresh program (no jit or
    # persistent-cache hit)
    c = float(np.random.default_rng().uniform(1.0, 2.0))
    jax.jit(lambda x: x * c)(jnp.ones(3)).block_until_ready()
    assert probes.counters()[0] > before


def test_snapshot_degrades_on_cpu():
    snap = probes.snapshot()
    assert snap["recompiles"] >= 0
    assert snap["transfer_bytes"] >= 0
    # CPU: memory_stats() is None -> key absent, live buffers fine
    assert "live_buffer_count" in snap
    assert isinstance(snap.get("device_memory", {}), dict)


def test_publish_sets_gauges():
    reg = MetricsRegistry(enabled=True)
    snap = probes.publish(reg)
    d = reg.as_dict()
    assert (
        d["repic_recompiles_total"]["samples"][0]["value"]
        == snap["recompiles"]
    )
    assert (
        d["repic_transfer_bytes_total"]["samples"][0]["value"]
        == snap["transfer_bytes"]
    )


def test_event_log_skips_torn_lines(tmp_path):
    path = tmp_path / "ev.jsonl"
    path.write_text(
        json.dumps({"ev": "event", "name": "a"})
        + "\n{\"ev\": \"spa"
    )
    records = tlm_events.read_events(str(path))
    assert [r["name"] for r in records] == ["a"]


# -- streaming sinks + per-host artifacts (ISSUE 7 tentpole) ---------


def test_read_events_merges_per_host_files(tmp_path):
    """Cluster runs leave one _events.<host>.jsonl per host; the
    directory read merges them in wall-clock order, tolerating a torn
    trailing line on any file (the crashed host's log is exactly the
    one the post-mortem reads) — the journal `_read_entries` parity
    contract."""
    (tmp_path / "_events.h1.jsonl").write_text(
        json.dumps({"ev": "event", "name": "a", "t": 1.0}) + "\n"
        + json.dumps({"ev": "event", "name": "c", "t": 3.0}) + "\n"
    )
    (tmp_path / "_events.h2.jsonl").write_text(
        json.dumps({"ev": "event", "name": "b", "t": 2.0}) + "\n"
        + '{"ev": "eve'  # torn mid-append by a host crash
    )
    records = tlm_events.read_events(str(tmp_path))
    assert [r["name"] for r in records] == ["a", "b", "c"]


def test_read_events_tolerates_missing_file():
    # OSError parity with journal._read_entries (deleted under us)
    assert tlm_events.read_events("/nonexistent/evlog.jsonl") == []


def test_host_events_name_sanitizes():
    assert tlm_events.host_events_name("h/1") == "_events.h_1.jsonl"


def test_start_run_per_host_artifact_names(tmp_path):
    from repic_tpu import telemetry

    rt = telemetry.start_run(
        str(tmp_path), host="h1", flush_interval_s=0
    )
    try:
        with tlm_events.span("stage_a"):
            pass
    finally:
        telemetry.finish_run(rt)
    assert (tmp_path / "_events.h1.jsonl").exists()
    assert (tmp_path / "_metrics.h1.json").exists()
    assert (tmp_path / "_metrics.h1.prom").exists()
    assert not (tmp_path / "_events.jsonl").exists()
    assert not (tmp_path / "_metrics.json").exists()
    by_host = sinks.read_all_metrics_json(str(tmp_path))
    assert list(by_host) == ["h1"]
    assert "repic_span_seconds" in by_host["h1"]


def test_flush_run_streams_sinks_mid_run(tmp_path):
    """flush_run rewrites the metric snapshots while the run is still
    open — the chunk-boundary streaming contract — and later flushes
    pick up new samples."""
    from repic_tpu import telemetry
    from repic_tpu.telemetry import metrics as tlm_metrics

    c = tlm_metrics.counter(
        "repic_flush_test_total", "streaming flush test"
    )
    rt = telemetry.start_run(str(tmp_path), flush_interval_s=0)
    try:
        c.inc(2)
        telemetry.flush_run(rt)
        assert (tmp_path / "_metrics.json").exists()
        mid = sinks.read_metrics_json(str(tmp_path))
        assert (
            mid["repic_flush_test_total"]["samples"][0]["value"] == 2
        )
        c.inc(3)
        telemetry.flush_run(rt)
        mid = sinks.read_metrics_json(str(tmp_path))
        assert (
            mid["repic_flush_test_total"]["samples"][0]["value"] == 5
        )
    finally:
        telemetry.finish_run(rt)
    # finish still finalizes (idempotent over the stream)
    final = sinks.read_metrics_json(str(tmp_path))
    assert final["repic_flush_test_total"]["samples"][0]["value"] == 5
    # and post-finish flushes are no-ops
    c.inc(100)
    telemetry.flush_run(rt)
    assert (
        sinks.read_metrics_json(str(tmp_path))[
            "repic_flush_test_total"
        ]["samples"][0]["value"]
        == 5
    )


def test_periodic_flusher_writes_without_explicit_flush(tmp_path):
    from repic_tpu import telemetry

    rt = telemetry.start_run(str(tmp_path), flush_interval_s=0.05)
    try:
        deadline = time.time() + 10.0
        while not (tmp_path / "_metrics.json").exists():
            assert time.time() < deadline, "flusher never fired"
            time.sleep(0.02)
    finally:
        telemetry.finish_run(rt)
    assert rt._flusher is not None and not rt._flusher.is_alive()


def test_flush_disabled_telemetry_is_noop(tmp_path, monkeypatch):
    from repic_tpu import telemetry
    from repic_tpu.telemetry import metrics as tlm_metrics

    monkeypatch.setattr(
        tlm_metrics.REGISTRY, "_enabled", False
    )
    rt = telemetry.start_run(str(tmp_path))
    telemetry.flush_run(rt)
    telemetry.finish_run(rt)
    assert list(tmp_path.iterdir()) == []


def test_prom_snapshot_carries_span_histogram(tmp_path):
    """Satellite: span durations land in the labeled
    repic_span_seconds histogram, so _metrics.prom carries latency
    distributions without parsing the event log."""
    from repic_tpu import telemetry

    rt = telemetry.start_run(str(tmp_path), flush_interval_s=0)
    try:
        with tlm_events.span("prom_hist_stage"):
            time.sleep(0.002)
    finally:
        telemetry.finish_run(rt)
    prom = (tmp_path / "_metrics.prom").read_text()
    assert (
        'repic_span_seconds_bucket{le="+Inf",name="prom_hist_stage"}'
        in prom
    )
    assert (
        'repic_span_seconds_count{name="prom_hist_stage"} 1' in prom
    )
