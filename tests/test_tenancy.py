"""Tenancy tests: keyfile, auth, rate/quota 429s, breaker scoping.

The ISSUE 14 tenancy surface: the keyfile parser is ValueError-or-
valid; ``Authorization: Bearer`` maps to 401/403/tenant; per-tenant
token-bucket and open-job/queued-micrograph quotas 429 with distinct
causes and refill-derived ``Retry-After`` in the same admission path
as the global queue-full check; idempotency keys are scoped per
tenant; the circuit breaker contains one tenant's failures; the
batcher's deal is tenant-fair; and — the acceptance gate — tenant A
saturating its quota draws 429s while tenant B's per-tenant SLO
bucket stays compliant and the shared breaker stays closed.
"""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from repic_tpu.serve import tenancy
from repic_tpu.serve.jobs import (
    JOB_FINISHED,
    AdmissionError,
    CircuitBreaker,
    JobQueue,
    ServeJournal,
)
from repic_tpu.serve.tenancy import (
    AuthError,
    TenantRegistry,
    TenantSpec,
    parse_tenants,
)

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "mini10017"
)
SUBMIT = {
    "in_dir": FIXTURE,
    "box_size": 180,
    "options": {"use_mesh": False},
}


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _registry(clk=None, **overrides):
    specs = [
        TenantSpec(name="teamA", keys=("ka",), **overrides),
        TenantSpec(name="teamB", keys=("kb",)),
    ]
    return TenantRegistry(specs, clock=clk or time.time)


# -- keyfile parsing ---------------------------------------------------


def test_parse_tenants_valid_and_resolve():
    specs = parse_tenants(
        {
            "tenants": [
                {
                    "name": "teamA",
                    "keys": ["sk-a-1", "sk-a-2"],
                    "rate": 2.0,
                    "burst": 4,
                    "max_open_jobs": 3,
                    "max_queued_micrographs": 64,
                },
                {"name": "teamB", "keys": ["sk-b"]},
            ]
        }
    )
    reg = TenantRegistry(specs)
    assert reg.names() == ["teamA", "teamB"]
    assert reg.resolve("Bearer sk-a-2") == "teamA"
    assert reg.resolve("bearer sk-b") == "teamB"  # scheme is
    # case-insensitive per RFC 7235
    spec = reg.spec("teamA")
    assert spec.rate == 2.0 and spec.max_open_jobs == 3


def test_parse_tenants_rejects_malformations():
    bad = [
        [],                                     # not an object
        {},                                     # no tenants
        {"tenants": []},                        # empty
        {"tenants": [{}]},                      # no name
        {"tenants": "teamA"},                   # wrong type
        {"tenants": [{"name": "a b", "keys": ["k"]}]},  # bad name
        {"tenants": [{"name": "a", "keys": []}]},       # no keys
        {"tenants": [{"name": "a", "keys": ["k"],
                      "typo": 1}]},             # unknown field
        {"tenants": [{"name": "a", "keys": ["k"],
                      "rate": 0}]},             # rate <= 0
        {"tenants": [{"name": "a", "keys": ["k"],
                      "rate": float("nan")}]},
        {"tenants": [{"name": "a", "keys": ["k"],
                      "burst": 0}]},
        {"tenants": [{"name": "a", "keys": ["k"],
                      "max_open_jobs": True}]},  # bool-as-int
        {"tenants": [{"name": "a", "keys": ["k"]},
                     {"name": "a", "keys": ["k2"]}]},  # dup name
        {"tenants": [{"name": "a", "keys": ["k"]},
                     {"name": "b", "keys": ["k"]}]},   # dup key
        {"tenants": [{"name": "anonymous",
                      "keys": ["k"]}]},         # anonymous w/ keys
        {"tenants": [{"name": "a", "keys": ["k\nx"]}]},  # newline
        {"extra": 1, "tenants": [{"name": "a", "keys": ["k"]}]},
    ]
    for doc in bad:
        with pytest.raises(ValueError):
            parse_tenants(doc)


def test_parse_tenants_priority_round_trip():
    """ISSUE 17: the ``priority`` brownout class parses, defaults to
    ``normal``, and resolves through the registry (unknown/None
    tenants read as normal — shedding must never KeyError)."""
    specs = parse_tenants(
        {
            "tenants": [
                {"name": "gold", "keys": ["kg"],
                 "priority": "high"},
                {"name": "std", "keys": ["ks"]},
                {"name": "bulk", "keys": ["kb"],
                 "priority": "low"},
            ]
        }
    )
    assert [s.priority for s in specs] == ["high", "normal", "low"]
    reg = TenantRegistry(specs)
    assert reg.priority("gold") == "high"
    assert reg.priority("std") == "normal"
    assert reg.priority("bulk") == "low"
    assert reg.priority("unknown") == "normal"
    assert reg.priority(None) == "normal"
    assert reg.describe("gold")["priority"] == "high"


def test_parse_tenants_rejects_bad_priority():
    for bad_priority in ("critical", "", 3, None):
        with pytest.raises(ValueError):
            parse_tenants(
                {
                    "tenants": [
                        {"name": "a", "keys": ["k"],
                         "priority": bad_priority}
                    ]
                }
            )


def test_load_tenants_unreadable_file_is_valueerror(tmp_path):
    with pytest.raises(ValueError):
        tenancy.load_tenants(str(tmp_path / "nope.json"))
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    with pytest.raises(ValueError):
        tenancy.load_tenants(str(p))


def test_anonymous_tenant_admits_keyless_requests():
    reg = TenantRegistry(
        [
            TenantSpec(name="anonymous", max_open_jobs=1),
            TenantSpec(name="teamA", keys=("ka",)),
        ]
    )
    assert reg.resolve(None) == "anonymous"
    assert reg.resolve("") == "anonymous"
    assert reg.resolve("Bearer ka") == "teamA"
    # a named-tenants-only registry refuses keyless outright
    reg2 = _registry()
    with pytest.raises(AuthError) as exc:
        reg2.resolve(None)
    assert exc.value.http_status == 401


def test_resolve_auth_error_codes():
    reg = _registry()
    for header, code in [
        ("Basic abc", 401),          # wrong scheme
        ("Bearer", 401),             # no key
        ("Bearer  ", 401),
        ("ka", 401),                 # bare key, no scheme
        ("Bearer " + "x" * 500, 401),  # oversized key
        ("Bearer nope", 403),        # well-formed, unknown
    ]:
        with pytest.raises(AuthError) as exc:
            reg.resolve(header)
        assert exc.value.http_status == code, header


# -- rate limits and quotas --------------------------------------------


def test_token_bucket_refill_and_retry_after():
    clk = Clock()
    reg = _registry(clk, rate=2.0, burst=2)

    def take():
        return reg.check_admission(
            "teamA", micrographs=1, open_jobs=0,
            queued_micrographs=0,
        )

    assert take() is None  # burst token 1
    assert take() is None  # burst token 2
    cause, retry = take()
    assert cause == "tenant_rate"
    assert retry == pytest.approx(0.5, abs=0.01)  # 1 token @ 2/s
    clk.advance(0.25)  # half a token back: still refused, sooner
    cause, retry = take()
    assert retry == pytest.approx(0.25, abs=0.01)
    clk.advance(0.5)
    assert take() is None  # refilled
    # teamB has no rate: never throttled
    for _ in range(10):
        assert reg.check_admission(
            "teamB", micrographs=1, open_jobs=0,
            queued_micrographs=0,
        ) is None


def test_quota_causes_and_retry_after_pricing():
    reg = _registry(
        max_open_jobs=2, max_queued_micrographs=10
    )
    ok = reg.check_admission(
        "teamA", micrographs=3, open_jobs=1,
        queued_micrographs=3,
    )
    assert ok is None
    cause, retry = reg.check_admission(
        "teamA", micrographs=1, open_jobs=2,
        queued_micrographs=3, per_mic_s=2.0,
    )
    assert cause == "tenant_open_jobs"
    assert retry == pytest.approx(6.0)  # 3 queued mics x 2 s
    cause, _ = reg.check_admission(
        "teamA", micrographs=8, open_jobs=1,
        queued_micrographs=3,
    )
    assert cause == "tenant_micrographs"  # 3 + 8 > 10
    # a job ALONE over the quota can never be admitted: the
    # permanent cause, not a retryable one
    cause, _ = reg.check_admission(
        "teamA", micrographs=11, open_jobs=0,
        queued_micrographs=0,
    )
    assert cause == "tenant_job_too_large"


def test_oversize_job_is_a_permanent_413(tmp_path):
    """A job intrinsically larger than the tenant's quota gets 413
    (permanent), not a 429 a polite client would replay forever."""
    reg = _registry(max_queued_micrographs=4)
    q = JobQueue(10, ServeJournal(str(tmp_path)), tenants=reg)
    with pytest.raises(AdmissionError) as exc:
        q.submit({"r": 1}, tenant="teamA", micrographs=5)
    assert exc.value.http_status == 413
    assert exc.value.reason == "tenant_job_too_large"
    # within-quota jobs still admit
    assert q.submit(
        {"r": 2}, tenant="teamA", micrographs=4
    ).state == "queued"


def test_queue_tenant_quota_429_in_admission_path(tmp_path):
    """The quota 429 rides the SAME AdmissionError surface as the
    global queue-full one, with its own cause — and one tenant's
    throttling never touches the other's admission."""
    reg = _registry(max_open_jobs=1)
    q = JobQueue(
        10, ServeJournal(str(tmp_path)), tenants=reg
    )
    q.submit({"r": 1}, tenant="teamA", micrographs=2)
    with pytest.raises(AdmissionError) as exc:
        q.submit({"r": 2}, tenant="teamA")
    assert exc.value.http_status == 429
    assert exc.value.reason == "tenant_open_jobs"
    assert exc.value.retry_after_s >= 1
    # tenant B sails through; so does a tenant-less submission
    assert q.submit({"r": 3}, tenant="teamB").tenant == "teamB"
    assert q.submit({"r": 4}).tenant is None
    # the accept record carries the tenant (journal attribution)
    from repic_tpu.runtime.journal import _read_entries

    entries = _read_entries(q.journal.path)
    accepts = {
        e.get("tenant")
        for e in entries
        if e.get("state") == "queued"
    }
    assert accepts == {"teamA", "teamB", None}


def test_queue_rate_limit_429(tmp_path):
    clk = Clock()
    reg = _registry(clk, rate=1.0, burst=1)
    q = JobQueue(
        10, ServeJournal(str(tmp_path)), tenants=reg, clock=clk
    )
    q.submit({"r": 1}, tenant="teamA")
    with pytest.raises(AdmissionError) as exc:
        q.submit({"r": 2}, tenant="teamA")
    assert exc.value.reason == "tenant_rate"
    clk.advance(1.1)
    assert q.submit({"r": 3}, tenant="teamA").state == "queued"


def test_idempotency_keys_scoped_per_tenant(tmp_path):
    q = JobQueue(10, ServeJournal(str(tmp_path)),
                 tenants=_registry())
    a, deduped_a = q.submit_idempotent(
        {"r": 1}, idempotency_key="k", tenant="teamA"
    )
    assert deduped_a is False
    b, deduped_b = q.submit_idempotent(
        {"r": 2}, idempotency_key="k", tenant="teamB"
    )
    # the SAME key under another tenant is a DIFFERENT job — a
    # cross-tenant alias would leak one tenant's job to another
    assert deduped_b is False
    assert b.id != a.id
    again, deduped = q.submit_idempotent(
        {"r": 3}, idempotency_key="k", tenant="teamA"
    )
    assert deduped is True and again.id == a.id


def test_dedupe_bypasses_tenant_throttle(tmp_path):
    """A retry of an ACCEPTED request must succeed even while the
    tenant is throttled — the durability promise was already made."""
    reg = _registry(max_open_jobs=1)
    q = JobQueue(10, ServeJournal(str(tmp_path)), tenants=reg)
    job = q.submit(
        {"r": 1}, idempotency_key="k", tenant="teamA"
    )
    with pytest.raises(AdmissionError):
        q.submit({"r": 2}, tenant="teamA")
    again, deduped = q.submit_idempotent(
        {"r": 1}, idempotency_key="k", tenant="teamA"
    )
    assert deduped is True and again.id == job.id


# -- breaker scoping ---------------------------------------------------


def test_breaker_contains_single_tenant_failures():
    t = Clock()
    b = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=t)
    b.record_failure("teamA")
    b.record_failure("teamA")
    # teamA's own breaker is open...
    with pytest.raises(AdmissionError) as exc:
        b.check_admission("teamA")
    assert exc.value.reason == "tenant_circuit_open"
    # ...but the SHARED breaker is not: teamB and anonymous admit
    b.check_admission("teamB")
    b.check_admission(None)
    desc = b.describe()
    assert desc["state"] == "closed"
    assert desc["tenants"]["teamA"]["state"] == "open"
    # cooldown -> half-open probe; a success closes teamA again
    t.advance(10.1)
    b.check_admission("teamA")
    b.record_success("teamA")
    b.check_admission("teamA")
    assert "teamA" not in b.describe().get("tenants", {})


def test_breaker_shared_trip_needs_two_tenants_at_threshold():
    t = Clock()
    b = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=t)
    # tenant A's long poison streak + ONE stray failure from B must
    # NOT trip the shared breaker (B's failure piggybacking on A's
    # streak is A's problem, not the backend's)
    for _ in range(20):
        b.record_failure("teamA")
    b.record_failure("teamB")
    b.check_admission("teamC")
    b.check_admission(None)
    # ...but B reaching the threshold ON ITS OWN means the backend
    # is failing everyone: the shared breaker opens
    b.record_failure("teamB")
    with pytest.raises(AdmissionError) as exc:
        b.check_admission("teamC")
    assert exc.value.reason == "circuit_open"


def test_breaker_tenantless_failures_keep_legacy_behavior():
    t = Clock()
    b = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=t)
    b.record_failure()
    b.record_failure()
    with pytest.raises(AdmissionError) as exc:
        b.check_admission()
    assert exc.value.reason == "circuit_open"


# -- batcher fair share ------------------------------------------------


def test_batcher_deal_is_tenant_fair():
    from types import SimpleNamespace

    from repic_tpu.serve.batcher import ContinuousBatcher

    def oj(tenant, pending):
        return SimpleNamespace(
            job=SimpleNamespace(tenant=tenant),
            pending=list(range(pending)),
        )

    # tenant A floods 3 jobs; tenant B has one job: the deal gives
    # each TENANT half the chunk, not each JOB a quarter
    a1, a2, a3, b1 = (
        oj("A", 10), oj("A", 10), oj("A", 10), oj("B", 10),
    )
    alloc = ContinuousBatcher._deal([a1, a2, a3, b1], 8)
    assert alloc[id(b1)] == 4
    assert (
        alloc[id(a1)] + alloc[id(a2)] + alloc[id(a3)] == 4
    )
    # single tenant (or tenancy off): the original per-job
    # round-robin equal split
    c1, c2 = oj(None, 10), oj(None, 10)
    alloc = ContinuousBatcher._deal([c1, c2], 8)
    assert alloc[id(c1)] == alloc[id(c2)] == 4
    # a tenant with less pending than its share: the remainder goes
    # to whoever has work (no dealt slots lost)
    d1, e1 = oj("A", 2), oj("B", 10)
    alloc = ContinuousBatcher._deal([d1, e1], 8)
    assert alloc[id(d1)] == 2 and alloc[id(e1)] == 6


# -- HTTP end to end ---------------------------------------------------


def _req(port, method, path, body=None, key=None, timeout=30):
    headers = {}
    if key is not None:
        headers["Authorization"] = f"Bearer {key}"
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=(
            json.dumps(body).encode() if body is not None else None
        ),
        headers=headers,
    )
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return (
                resp.status, dict(resp.headers),
                resp.read().decode(),
            )
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


def _wait_terminal(port, job_id, key, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        code, _, body = _req(
            port, "GET", f"/v1/jobs/{job_id}", key=key
        )
        assert code == 200, body
        doc = json.loads(body)
        if doc["state"] not in ("queued", "running"):
            return doc
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never became terminal")


@pytest.fixture
def tenant_daemon(tmp_path):
    from repic_tpu.serve.daemon import ConsensusDaemon

    d = ConsensusDaemon(
        str(tmp_path / "wd"),
        port=0,
        queue_limit=16,
        warmup=False,
        drain_grace_s=10.0,
        tenants=_registry(max_open_jobs=2),
        slo_targets={"job": (300.0, 0.95)},
    )
    d.start()
    yield d
    if not d.queue.draining:
        d.drain()


def test_http_auth_and_tenant_attribution(tenant_daemon):
    port = tenant_daemon.server.port
    # 401 without a key (WWW-Authenticate present), 403 unknown key
    code, headers, _ = _req(port, "POST", "/v1/jobs", SUBMIT)
    assert code == 401
    assert headers.get("WWW-Authenticate") == "Bearer"
    code, _, _ = _req(
        port, "POST", "/v1/jobs", SUBMIT, key="wrong"
    )
    assert code == 403
    # health/metrics stay open (no tenant data, 127.0.0.1 only)
    assert _req(port, "GET", "/healthz/live")[0] == 200
    assert _req(port, "GET", "/metrics")[0] == 200
    # authenticated submit: 202, attributed end to end
    code, _, body = _req(
        port, "POST", "/v1/jobs", SUBMIT, key="ka"
    )
    assert code == 202, body
    doc = json.loads(body)
    assert doc["tenant"] == "teamA"
    jid = doc["id"]
    done = _wait_terminal(port, jid, "ka")
    assert done["state"] == "finished", done
    # tenant isolation on the read surface
    code, _, _ = _req(port, "GET", f"/v1/jobs/{jid}", key="kb")
    assert code == 403
    code, _, _ = _req(
        port, "GET", f"/v1/jobs/{jid}/artifacts", key="kb"
    )
    assert code == 403
    code, _, body = _req(port, "GET", "/v1/jobs", key="kb")
    assert code == 200
    assert json.loads(body)["jobs"] == []
    code, _, body = _req(port, "GET", "/v1/jobs", key="ka")
    assert {j["id"] for j in json.loads(body)["jobs"]} == {jid}
    # journal + trace attribution
    from repic_tpu.runtime.journal import _read_entries

    accept = next(
        e
        for e in _read_entries(tenant_daemon.journal.path)
        if e.get("job") == jid and e.get("state") == "queued"
    )
    assert accept["tenant"] == "teamA"
    trace_path = os.path.join(
        tenant_daemon.job_dir(jid), "_trace.jsonl"
    )
    roots = [
        e
        for e in _read_entries(trace_path)
        if e.get("tenant") == "teamA"
    ]
    assert roots, "trace root lost the tenant"
    # per-tenant metrics + /status tenants section
    _, _, metrics = _req(port, "GET", "/metrics")
    assert 'repic_tenant_admitted_total{tenant="teamA"}' in metrics
    assert 'repic_tenant_jobs_total' in metrics
    _, _, status = _req(port, "GET", "/status")
    tenants = json.loads(status)["tenants"]
    assert set(tenants) == {"teamA", "teamB"}
    assert tenants["teamA"]["max_open_jobs"] == 2


def test_tenant_isolation_quota_429_vs_b_slo(tenant_daemon):
    """The ISSUE 14 isolation gate: tenant A saturating ITS quota
    draws tenant-cause 429s while tenant B's jobs run to completion
    with a fully compliant per-tenant SLO bucket — and A's
    throttling never opens the shared breaker."""
    port = tenant_daemon.server.port
    # saturate A's max_open_jobs=2
    a_codes = []
    for _ in range(6):
        code, headers, body = _req(
            port, "POST", "/v1/jobs", SUBMIT, key="ka"
        )
        a_codes.append(code)
        if code == 429:
            assert "tenant_" in body, body
            assert int(headers["Retry-After"]) >= 1
    assert a_codes.count(429) >= 2, a_codes
    # B's traffic proceeds normally through the same daemon
    b_ids = []
    for _ in range(2):
        code, _, body = _req(
            port, "POST", "/v1/jobs", SUBMIT, key="kb"
        )
        assert code == 202, body
        b_ids.append(json.loads(body)["id"])
    for jid in b_ids:
        assert (
            _wait_terminal(port, jid, "kb")["state"] == "finished"
        )
    # B's per-tenant SLO bucket: compliant, with the `job`
    # objective inherited (telemetry.server tenant: fallback)
    slo = tenant_daemon.slo.summary()["endpoints"]
    b_ep = slo["tenant:teamB"]
    assert b_ep["count"] == 2, b_ep
    assert b_ep["compliance"] == 1.0, b_ep
    assert b_ep["budget_burn"] == 0.0, b_ep
    # the shared breaker never heard about A's throttling
    assert tenant_daemon.queue.breaker.describe()["state"] == (
        "closed"
    )
    # A's rejects are attributed on /status
    _, _, status = _req(port, "GET", "/status")
    rejected = json.loads(status)["tenants"]["teamA"].get(
        "rejected", {}
    )
    assert sum(rejected.values()) >= 2, rejected


def test_daemon_rejects_bad_tenants_file(tmp_path):
    from repic_tpu.serve.daemon import ConsensusDaemon

    bad = tmp_path / "tenants.json"
    bad.write_text('{"tenants": []}')
    with pytest.raises(ValueError):
        ConsensusDaemon(
            str(tmp_path / "wd"), warmup=False,
            tenants=str(bad),
        )


def test_queue_finish_counts_tenant_jobs(tmp_path):
    q = JobQueue(10, ServeJournal(str(tmp_path)),
                 tenants=_registry())
    job = q.submit({"r": 1}, tenant="teamA")
    assert q.next_job(0.01).id == job.id
    q.mark_running(job)
    q.finish(job, JOB_FINISHED)
    assert (
        tenancy._TENANT_JOBS.value(
            tenant="teamA", state="finished"
        )
        >= 1
    )
