"""Request-scoped tracing: context propagation, the per-request
trace artifact, `repic-tpu trace`, and the SLO plane.

The ISSUE 10 acceptance surface: a serve job's trace id flows from
HTTP accept across the worker-thread handoff into every span /
journal record / trace segment; the torn artifact a crashed job
leaves still renders a partial waterfall; the SLO tracker turns
rolling observations into p50/p95/p99 + error-budget burn on
`/status`.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from repic_tpu.main import main as cli_main
from repic_tpu.telemetry import server as tlm_server
from repic_tpu.telemetry import trace as tlm_trace

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "mini10017"
)


# -- trace context + artifact ----------------------------------------


def test_scope_writes_root_and_segments(tmp_path):
    out = str(tmp_path)
    with tlm_trace.scope(out, kind="cli", job="j1") as ctx:
        assert tlm_trace.current_trace_id() == ctx.trace_id
        tlm_trace.add_segment("plan", 1.0, 0.25, micrographs=3)
        with tlm_trace.segment("emit", chunk=0):
            time.sleep(0.01)
    assert tlm_trace.current_trace_id() is None
    records = tlm_trace.read_trace(out)
    assert [r["ev"] for r in records] == [
        "trace", "segment", "segment"
    ]
    root, plan, emit = records
    assert root["kind"] == "cli" and root["job"] == "j1"
    assert {r["trace"] for r in records} == {ctx.trace_id}
    assert plan["seg"] == "plan" and plan["dur_s"] == 0.25
    assert emit["seg"] == "emit" and emit["dur_s"] >= 0.01


def test_add_segment_is_noop_without_active_context(tmp_path):
    tlm_trace.add_segment("execute", 0.0, 1.0)  # must not raise
    ctx = tlm_trace.start(None)  # id-only context, no artifact
    token = tlm_trace.activate(ctx)
    try:
        tlm_trace.add_segment("execute", 0.0, 1.0)
    finally:
        tlm_trace.deactivate(token)
        ctx.close()
    assert not os.path.exists(tlm_trace.trace_path(str(tmp_path)))


def test_spans_events_and_journal_records_carry_trace_id(tmp_path):
    """While a context is active, the whole telemetry plane joins to
    the request: span exits, point events, log records, and run-
    journal appends all carry the trace id — and none of them do
    once the context is gone."""
    from repic_tpu import telemetry
    from repic_tpu.runtime.journal import RunJournal
    from repic_tpu.telemetry import events as tlm_events

    out = str(tmp_path)
    rt = telemetry.start_run(out)
    try:
        with tlm_trace.scope(out, kind="cli") as ctx:
            with tlm_events.span("traced_stage"):
                pass
            tlm_events.event("traced_event")
            j = RunJournal(out)
            j.record("mic0", "ok")
            j.close()
        with tlm_events.span("untraced_stage"):
            pass
    finally:
        telemetry.finish_run(rt)
    events = tlm_events.read_events(out)
    by_name = {r.get("name"): r for r in events if "name" in r}
    assert by_name["traced_stage"]["trace"] == ctx.trace_id
    assert by_name["traced_event"]["trace"] == ctx.trace_id
    assert "trace" not in by_name["untraced_stage"]
    journal = [
        json.loads(line)
        for line in open(os.path.join(out, "_journal.jsonl"))
    ]
    assert journal[0]["trace"] == ctx.trace_id


def test_thread_target_propagates_context(tmp_path):
    """threading.Thread does not inherit contextvars; thread_target
    captures the caller's context so a hand-rolled handoff keeps the
    trace id."""
    seen = {}

    def probe(key):
        seen[key] = tlm_trace.current_trace_id()

    with tlm_trace.scope(str(tmp_path)) as ctx:
        bare = threading.Thread(target=probe, args=("bare",))
        bound = threading.Thread(
            target=tlm_trace.thread_target(probe, "bound")
        )
        bare.start(), bound.start()
        bare.join(), bound.join()
    assert seen["bare"] is None
    assert seen["bound"] == ctx.trace_id


def test_summarize_totals_cache_and_span(tmp_path):
    recs = [
        {"ev": "trace", "trace": "t1", "t": 10.0, "kind": "serve",
         "job": "j1"},
        {"ev": "segment", "trace": "t1", "seg": "queue_wait",
         "t": 10.0, "dur_s": 1.0},
        {"ev": "segment", "trace": "t1", "seg": "compile",
         "t": 11.0, "dur_s": 2.0, "cache_hits": 0,
         "cache_misses": 3},
        {"ev": "segment", "trace": "t1", "seg": "execute",
         "t": 13.0, "dur_s": 0.5},
        {"ev": "segment", "trace": "t1", "seg": "execute",
         "t": 13.5, "dur_s": 0.5},
    ]
    tr = tlm_trace.summarize(recs)["t1"]
    assert tr["kind"] == "serve" and tr["job"] == "j1"
    assert tr["segment_totals"] == {
        "queue_wait": 1.0, "compile": 2.0, "execute": 1.0
    }
    assert tr["total_s"] == pytest.approx(4.0)
    assert tr["span_s"] == pytest.approx(4.0)  # 10.0 -> 14.0
    assert tr["cache"] == {"hits": 0, "misses": 3}


def test_critical_path_serial_and_overlapping():
    serial = [
        {"seg": "a", "t": 0.0, "dur_s": 1.0},
        {"seg": "b", "t": 1.0, "dur_s": 2.0},
        {"seg": "c", "t": 3.0, "dur_s": 0.5},
    ]
    assert [s["seg"] for s in tlm_trace.critical_path(serial)] == [
        "a", "b", "c"
    ]
    # an overlapped short segment never makes the path; a real gap
    # jumps to the next segment
    overlap = [
        {"seg": "a", "t": 0.0, "dur_s": 2.0},
        {"seg": "inner", "t": 0.5, "dur_s": 0.5},
        {"seg": "late", "t": 5.0, "dur_s": 1.0},
    ]
    assert [s["seg"] for s in tlm_trace.critical_path(overlap)] == [
        "a", "late"
    ]
    assert tlm_trace.critical_path([]) == []


def test_torn_tail_artifact_still_renders(tmp_path, capsys):
    """Crash mid-job: the trace artifact tears at the trailing line
    and `repic-tpu trace` still renders the partial waterfall."""
    out = str(tmp_path)
    with tlm_trace.scope(out, kind="serve", job="j9") as ctx:
        tlm_trace.add_segment("queue_wait", 100.0, 0.5)
        tlm_trace.add_segment("execute", 100.5, 2.0, chunk=0)
    with open(tlm_trace.trace_path(out), "a") as f:
        f.write('{"ev": "segment", "trace": "' + ctx.trace_id)
    records = tlm_trace.read_trace(out)
    assert len(records) == 3  # torn line dropped, prefix kept
    cli_main(["trace", out])
    rendered = capsys.readouterr().out
    assert ctx.trace_id in rendered
    assert "queue_wait" in rendered and "execute[0]" in rendered
    assert "critical path" in rendered


def test_trace_cli_json_and_missing_artifact(tmp_path, capsys):
    out = str(tmp_path)
    with tlm_trace.scope(out, kind="cli") as ctx:
        tlm_trace.add_segment("execute", 1.0, 1.0)
    cli_main(["trace", out, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["traces"][ctx.trace_id]["segment_totals"] == {
        "execute": 1.0
    }
    with pytest.raises(SystemExit):
        cli_main(["trace", str(tmp_path / "nowhere")])


def test_cluster_per_host_trace_files_merge(tmp_path):
    """Cluster runs share one out_dir: each host writes its own
    ``_trace.<host>.jsonl`` (the journal/event scheme — N appenders
    on one file would interleave) and read_trace merges them."""
    out = str(tmp_path)
    tids = {}
    for host in ("h1", "h2"):
        ctx = tlm_trace.start(out, host=host, kind="cli")
        token = tlm_trace.activate(ctx)
        try:
            tlm_trace.add_segment("execute", 1.0, 1.0)
        finally:
            tlm_trace.deactivate(token)
            ctx.close()
        tids[host] = ctx.trace_id
    assert not os.path.exists(tlm_trace.trace_path(out))
    for host in ("h1", "h2"):
        assert os.path.exists(tlm_trace.trace_path(out, host=host))
    summaries = tlm_trace.summarize(tlm_trace.read_trace(out))
    assert set(summaries) == set(tids.values())


def test_trace_cli_lists_jobs_in_work_dir(tmp_path, capsys):
    """Pointed at a serve work_dir without a job id, the command
    lists the jobs that carry trace artifacts."""
    jobs = tmp_path / "jobs"
    for jid in ("j1", "j2"):
        with tlm_trace.scope(str(jobs / jid), job=jid):
            tlm_trace.add_segment("execute", 1.0, 1.0)
    os.makedirs(jobs / "j3")  # no artifact -> not listed
    cli_main(["trace", str(tmp_path)])
    out = capsys.readouterr().out
    assert "j1" in out and "j2" in out and "j3" not in out
    cli_main(["trace", str(tmp_path), "j2"])
    assert "execute" in capsys.readouterr().out


# -- SLO plane --------------------------------------------------------


def test_parse_slo_targets():
    from repic_tpu.telemetry.server import parse_slo_targets

    assert parse_slo_targets(None) == {}
    assert parse_slo_targets(
        ["job=60", "queue_wait=5@0.99"]
    ) == {"job": (60.0, 0.95), "queue_wait": (5.0, 0.99)}
    for bad in ("job", "job=0", "job=10@1.5", "=5", "job=x"):
        with pytest.raises(ValueError):
            parse_slo_targets([bad])


def test_slo_tracker_percentiles_and_burn():
    tracker = tlm_server.SLOTracker(
        objectives={"job": (1.0, 0.9)}, window=100
    )
    # 8 fast + 2 over-target -> 20% violating, budget 10% -> burn 2x
    for _ in range(8):
        tracker.observe("job", 0.5)
    tracker.observe("job", 3.0)
    tracker.observe("job", 4.0, ok=False)
    ep = tracker.summary()["endpoints"]["job"]
    assert ep["count"] == 10
    assert ep["p50_s"] == pytest.approx(0.5)
    assert ep["p99_s"] == pytest.approx(4.0)
    assert ep["compliance"] == pytest.approx(0.8)
    assert ep["budget_burn"] == pytest.approx(2.0)
    # an endpoint without an objective reports percentiles only
    tracker.observe("queue_wait", 0.1)
    qw = tracker.summary()["endpoints"]["queue_wait"]
    assert "budget_burn" not in qw and qw["p50_s"] > 0


def test_slo_tracker_buckets_and_window():
    tracker = tlm_server.SLOTracker(window=4)
    for cap, lat in ((256, 0.1), (256, 0.2), (512, 1.0)):
        tracker.observe("job", lat, bucket=cap)
    ep = tracker.summary()["endpoints"]["job"]
    assert set(ep["by_bucket"]) == {"256", "512"}
    assert ep["by_bucket"]["512"]["p50_s"] == pytest.approx(1.0)
    # the rolling window keeps only the newest entries per key
    for i in range(10):
        tracker.observe("job", float(i), bucket=256)
    assert (
        tracker.summary()["endpoints"]["job"]["by_bucket"]["256"][
            "count"
        ]
        == 4
    )


def test_observe_slo_noop_without_tracker():
    assert tlm_server.get_slo_tracker() is None
    tlm_server.observe_slo("job", 1.0)  # must not raise


def test_queued_cancel_counts_as_slo_violation(tmp_path):
    """A job cancelled while queued reaches terminal without passing
    through the daemon's _finish_job — the queue itself must feed
    the SLO plane, or compliance overstates health
    (docs/serving.md: cancelled jobs count as violations)."""
    from repic_tpu.serve.jobs import JobQueue, ServeJournal

    tracker = tlm_server.SLOTracker(objectives={"job": (60.0, 0.9)})
    prev = tlm_server.set_slo_tracker(tracker)
    try:
        q = JobQueue(4, ServeJournal(str(tmp_path)))
        job = q.submit({"in_dir": "x"})
        q.cancel(job.id)
        ep = tracker.summary()["endpoints"]["job"]
        assert ep["count"] == 1
        assert ep["compliance"] == 0.0
    finally:
        tlm_server.set_slo_tracker(prev)


def test_status_endpoint_reports_slo_section():
    tracker = tlm_server.SLOTracker(objectives={"job": (60.0, 0.95)})
    tracker.observe("job", 1.5)
    prev = tlm_server.set_slo_tracker(tracker)
    srv = tlm_server.StatusServer(port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/status", timeout=5
        ) as resp:
            doc = json.loads(resp.read().decode())
        slo = doc["slo"]
        assert slo["objectives"]["job"]["target_s"] == 60.0
        assert slo["endpoints"]["job"]["p95_s"] > 0
        # the HTTP handling itself feeds the rolling view: the
        # first scrape predates its own observation, so scrape again
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/status", timeout=5
        ) as resp:
            doc = json.loads(resp.read().decode())
        assert "http:status" in doc["slo"]["endpoints"]
    finally:
        srv.stop()
        tlm_server.set_slo_tracker(prev)


def test_route_labels_are_bounded():
    from repic_tpu.telemetry.server import _route

    assert _route("/v1/jobs") == "jobs"
    assert _route("/v1/jobs/abc123") == "job"
    assert _route("/v1/jobs/abc123/artifacts") == "artifacts"
    assert _route("/v1/jobs/abc123/artifacts/m1.box") == "artifacts"
    assert _route("/healthz/ready") == "healthz"
    assert _route("/metrics") == "metrics"
    assert _route("/status") == "status"
    assert _route("/favicon.ico") == "other"


# -- daemon handoff (integration) ------------------------------------


@pytest.mark.slow
def test_serve_job_trace_follows_worker_handoff(tmp_path):
    """The tentpole end-to-end, in process: the trace id minted at
    HTTP accept survives the queue residency and the worker-thread
    handoff; the job directory's artifact carries contiguous
    queue_wait/plan/compile|execute/emit segments whose sum tracks
    the job wall time; journal + span records join by the same id."""
    from repic_tpu.serve.daemon import ConsensusDaemon
    from repic_tpu.telemetry import events as tlm_events

    d = ConsensusDaemon(
        str(tmp_path / "wd"), port=0, warmup=False,
        slo_targets={"job": (300.0, 0.95)},
    )
    d.start()
    try:
        body = json.dumps({
            "in_dir": FIXTURE, "box_size": 180,
            "options": {"use_mesh": False},
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{d.server.port}/v1/jobs",
            data=body, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            accepted = json.loads(resp.read().decode())
        tid = accepted["trace_id"]
        assert tid
        deadline = time.time() + 120
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{d.server.port}/v1/jobs/"
                + accepted["id"],
                timeout=10,
            ) as resp:
                doc = json.loads(resp.read().decode())
            if doc["state"] in (
                "finished", "failed", "cancelled",
                "deadline_exceeded",
            ):
                break
            time.sleep(0.1)
        assert doc["state"] == "finished", doc
        job_dir = os.path.join(
            str(tmp_path / "wd"), "jobs", accepted["id"]
        )
        summaries = tlm_trace.summarize(
            tlm_trace.read_trace(job_dir)
        )
        assert list(summaries) == [tid]
        tr = summaries[tid]
        segs = tr["segment_totals"]
        assert {"queue_wait", "plan", "execute", "emit"} <= set(segs)
        # acceptance: segment sum within 10% of the job wall time
        wall = doc["finished_ts"] - doc["accepted_ts"]
        assert tr["total_s"] == pytest.approx(wall, rel=0.10)
        # journal + spans in the job dir joined by the same id
        journal = [
            json.loads(line)
            for line in open(
                os.path.join(job_dir, "_journal.jsonl")
            )
        ]
        assert journal and all(
            r.get("trace") == tid for r in journal
        )
        spans = [
            r
            for r in tlm_events.read_events(job_dir)
            if r.get("ev") == "span"
        ]
        assert spans and all(r.get("trace") == tid for r in spans)
        # the SLO plane saw the job land
        slo = d.slo.summary()["endpoints"]
        assert slo["job"]["count"] == 1
        assert slo["job"]["compliance"] == 1.0
        assert slo["queue_wait"]["count"] == 1
    finally:
        d.drain()
