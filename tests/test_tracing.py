"""utils/tracing.py: StageTimer semantics (previously untested).

Covers the two historical bugs fixed with the telemetry PR — wall
clock instead of ``perf_counter``, and ``as_dict`` silently dropping
repeated stage labels — plus the TSV surface and the no-op contracts
of ``trace_session``/``annotate``.
"""

import os
import time

from repic_tpu.telemetry import events as tlm_events
from repic_tpu.telemetry import metrics as tlm_metrics
from repic_tpu.utils import tracing
from repic_tpu.utils.tracing import StageTimer, annotate, trace_session


def test_stage_records_positive_duration():
    timer = StageTimer()
    with timer.stage("work"):
        time.sleep(0.005)
    assert len(timer.stages) == 1
    label, secs = timer.stages[0]
    assert label == "work"
    assert 0.004 <= secs < 5.0


def test_stage_uses_perf_counter_not_wall_clock(monkeypatch):
    """A wall-clock jump (NTP adjustment) must not corrupt stage
    durations: with telemetry disabled the shim must never touch
    ``time.time`` at all."""
    monkeypatch.setattr(tlm_metrics.REGISTRY, "_enabled", False)

    def boom():  # pragma: no cover - failing path
        raise AssertionError("StageTimer used wall-clock time.time")

    monkeypatch.setattr(time, "time", boom)
    timer = StageTimer()
    with timer.stage("work"):
        pass
    assert timer.stages[0][1] >= 0.0


def test_as_dict_aggregates_repeated_labels():
    """Repeated labels sum — the old dict comprehension kept only the
    last occurrence (chunked runs emit 'compute' once per chunk)."""
    timer = StageTimer()
    timer.stages = [("compute", 1.0), ("write", 0.5), ("compute", 2.0)]
    d = timer.as_dict()
    assert d == {"compute": 3.0, "write": 0.5}


def test_stage_records_on_exception():
    timer = StageTimer()
    try:
        with timer.stage("fails"):
            raise ValueError("boom")
    except ValueError:
        pass
    assert [label for label, _ in timer.stages] == ["fails"]


def test_write_tsv_keeps_reference_shape(tmp_path):
    """One ``stage<TAB>seconds`` row per stage, duplicates preserved
    as separate rows (the reference's appending-writer behavior)."""
    timer = StageTimer()
    timer.stages = [("load", 0.25), ("compute", 1.5), ("load", 0.75)]
    path = timer.write_tsv(str(tmp_path))
    rows = [
        line.split("\t")
        for line in open(path).read().splitlines()
    ]
    assert [r[0] for r in rows] == ["load", "compute", "load"]
    assert [float(r[1]) for r in rows] == [0.25, 1.5, 0.75]
    assert os.path.basename(path) == "runtime.tsv"


def test_stage_emits_telemetry_span(tmp_path):
    """StageTimer is a shim over the span layer: with a run log
    active, each stage appends one span record."""
    log = tlm_events.EventLog(str(tmp_path / "ev.jsonl"))
    prev = tlm_events.set_current_log(log)
    try:
        timer = StageTimer()
        with timer.stage("load"):
            pass
    finally:
        tlm_events.set_current_log(prev)
        log.close()
    records = tlm_events.read_events(str(tmp_path / "ev.jsonl"))
    spans = [r for r in records if r.get("ev") == "span"]
    assert len(spans) == 1
    assert spans[0]["name"] == "load"
    assert spans[0]["kind"] == "stage"
    assert spans[0]["dur_s"] >= 0.0


def test_trace_session_none_is_noop(tmp_path):
    ran = []
    with trace_session(None):
        ran.append(True)
    assert ran == [True]


def test_annotate_is_reentrant_context():
    with annotate("outer"):
        with annotate("inner"):
            pass
