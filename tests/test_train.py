"""Training-pipeline tests: patch extraction semantics, a tiny
end-to-end fit on planted synthetic particles (val error must
collapse), warm-start, and the fit CLI."""

import os

import numpy as np
import pytest

from repic_tpu.models import data as data_mod
from repic_tpu.models.train import TrainConfig, fit
from repic_tpu.utils import mrc
from repic_tpu.utils.box_io import write_box

PARTICLE = 120  # binned patch = 40px, NMS window = 6 (realistic scale)


def make_micrograph(rng, size=800, n_particles=12, particle=PARTICLE):
    """Noise background with bright Gaussian blobs planted on a
    jittered grid; returns (image, centers)."""
    img = rng.normal(0, 1.0, size=(size, size)).astype(np.float32)
    centers = []
    margin = particle
    grid = np.linspace(margin, size - margin, 4)
    yy, xx = np.meshgrid(grid, grid)
    pts = np.column_stack([xx.ravel(), yy.ravel()])
    pts = pts[rng.permutation(len(pts))[:n_particles]]
    rad = particle / 4
    y, x = np.mgrid[0:size, 0:size]
    for cx, cy in pts + rng.normal(0, 3, size=(len(pts), 2)):
        blob = 6.0 * np.exp(
            -((x - cx) ** 2 + (y - cy) ** 2) / (2 * rad**2)
        )
        img += blob.astype(np.float32)
        centers.append((cx, cy))
    return img, np.array(centers)


def write_pair(dirs, stem, img, centers, particle=PARTICLE):
    mrc_dir, box_dir = dirs
    mrc.write_mrc(os.path.join(mrc_dir, stem + ".mrc"), img)
    write_box(
        os.path.join(box_dir, stem + ".box"),
        centers - particle / 2,
        np.ones(len(centers)),
        particle,
    )


@pytest.fixture(scope="module")
def synthetic_dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("synth")
    rng = np.random.default_rng(7)
    dirs = {}
    for split, n in [("train", 3), ("val", 1)]:
        mrc_dir = root / f"{split}_mrc"
        box_dir = root / f"{split}_box"
        mrc_dir.mkdir()
        box_dir.mkdir()
        for i in range(n):
            img, centers = make_micrograph(rng)
            write_pair(
                (str(mrc_dir), str(box_dir)), f"{split}{i}", img, centers
            )
        dirs[split] = (str(mrc_dir), str(box_dir))
    return dirs


def test_extract_patches_counts_and_shapes(rng):
    img, centers = make_micrograph(rng)
    pos, neg = data_mod.extract_micrograph_patches(
        img, centers, PARTICLE, rng
    )
    p = 2 * (int(PARTICLE / 3) // 2)
    assert pos.shape[1:] == (p, p)
    assert neg.shape == pos.shape
    assert len(pos) == len(centers)


def test_negatives_avoid_positives(rng):
    img, centers = make_micrograph(rng, n_particles=4)
    # use the rejection rule directly: re-run and check all sampled
    # negative patch centers are far from positives by reconstructing
    # distance from patch content is fragile; instead verify via a
    # tight seed-driven re-implementation
    pos, neg = data_mod.extract_micrograph_patches(
        img, centers, PARTICLE, np.random.default_rng(3)
    )
    assert len(neg) == len(pos)


def test_boundary_coordinates_dropped(rng):
    img, _ = make_micrograph(rng, n_particles=0)
    centers = np.array([[2.0, 2.0], [300.0, 300.0]])
    pos, neg = data_mod.extract_micrograph_patches(
        img, centers, PARTICLE, rng
    )
    assert len(pos) == 1  # corner particle clipped


def test_load_dataset_balanced(synthetic_dataset):
    mrc_dir, box_dir = synthetic_dataset["train"]
    data, labels = data_mod.load_dataset(mrc_dir, box_dir, PARTICLE)
    assert data.shape[1:] == (64, 64, 1)
    assert labels.sum() * 2 == len(labels)
    # per-patch standardization
    assert abs(float(data[0].mean())) < 1e-4


def test_load_dataset_missing_pairs(tmp_path):
    (tmp_path / "mrc").mkdir()
    (tmp_path / "box").mkdir()
    with pytest.raises(FileNotFoundError):
        data_mod.load_dataset(
            str(tmp_path / "mrc"), str(tmp_path / "box"), PARTICLE
        )


@pytest.fixture(scope="module")
def trained(synthetic_dataset):
    train_data, train_labels = data_mod.load_dataset(
        *synthetic_dataset["train"], PARTICLE
    )
    val_data, val_labels = data_mod.load_dataset(
        *synthetic_dataset["val"], PARTICLE
    )
    config = TrainConfig(
        batch_size=16, max_epochs=30, patience=10, verbose=False
    )
    return fit(train_data, train_labels, val_data, val_labels, config)


def test_fit_learns_synthetic_blobs(trained):
    # planted bright blobs vs noise: near-perfect separation expected
    assert trained.best_val_error <= 10.0
    assert trained.history[0]["val_error"] >= trained.best_val_error


def test_fit_warm_start(synthetic_dataset, trained):
    train_data, train_labels = data_mod.load_dataset(
        *synthetic_dataset["train"], PARTICLE
    )
    val_data, val_labels = data_mod.load_dataset(
        *synthetic_dataset["val"], PARTICLE
    )
    config = TrainConfig(
        batch_size=16, max_epochs=2, patience=5, verbose=False
    )
    result = fit(
        train_data,
        train_labels,
        val_data,
        val_labels,
        config,
        init_params=trained.params,
    )
    # warm start should keep the solved problem solved
    assert result.best_val_error <= trained.best_val_error + 5.0


def test_trained_model_picks_planted_particles(trained):
    from repic_tpu.models.infer import pick_micrograph

    rng = np.random.default_rng(99)
    img, centers = make_micrograph(rng)
    coords = pick_micrograph(
        trained.params, img, PARTICLE, mode="patch"
    )
    strong = coords[coords[:, 2] > 0.5]
    # every planted particle should have a strong pick nearby
    found = 0
    for cx, cy in centers:
        d = np.hypot(strong[:, 0] - cx, strong[:, 1] - cy)
        if len(d) and d.min() < PARTICLE / 2:
            found += 1
    assert found >= len(centers) * 0.75


def test_bf16_training_matches_f32(synthetic_dataset, trained):
    """bfloat16 compute (f32 master weights) must solve the planted
    problem to within 1.5% val error of the float32 run, with params
    still stored float32 for checkpoint compatibility."""
    import jax
    import jax.numpy as jnp

    train_data, train_labels = data_mod.load_dataset(
        *synthetic_dataset["train"], PARTICLE
    )
    val_data, val_labels = data_mod.load_dataset(
        *synthetic_dataset["val"], PARTICLE
    )
    config = TrainConfig(
        batch_size=16, max_epochs=30, patience=10, verbose=False,
        compute_dtype="bfloat16",
    )
    result = fit(train_data, train_labels, val_data, val_labels, config)
    assert result.best_val_error <= trained.best_val_error + 1.5
    for leaf in jax.tree_util.tree_leaves(result.params):
        assert leaf.dtype == jnp.float32 or leaf.dtype == np.float32


@pytest.mark.parametrize("mode", ["patch", "fcn"])
def test_bf16_scoring_close_to_f32(trained, mode):
    """The same trained f32 params scored under bfloat16 compute must
    yield near-identical picks in BOTH inference modes (the fcn path
    goes through fc_params_as_conv-reshaped params)."""
    from repic_tpu.models.infer import pick_micrograph

    rng = np.random.default_rng(7)
    img, centers = make_micrograph(rng, n_particles=6)
    a = pick_micrograph(
        trained.params, img, PARTICLE, mode=mode, dtype="float32"
    )
    b = pick_micrograph(
        trained.params, img, PARTICLE, mode=mode, dtype="bfloat16"
    )
    # peak sets may differ at the margin; strong picks must agree
    sa = a[a[:, 2] > 0.7]
    sb = b[b[:, 2] > 0.7]
    assert abs(len(sa) - len(sb)) <= max(2, 0.2 * len(sa))
    for cx, cy, _ in sa:
        d = np.hypot(sb[:, 0] - cx, sb[:, 1] - cy)
        assert len(d) and d.min() < PARTICLE / 2


def test_bf16_score_maps_close_to_f32():
    """Raw score maps (pre-peak-detection) under bf16 compute must
    match f32 to ~1e-2 — the quantitative claim behind the CLI help."""
    from repic_tpu.models import preprocess as pp
    from repic_tpu.models.cnn import PickerCNN
    from repic_tpu.models.infer import score_micrograph_patches

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    img, _ = make_micrograph(rng, n_particles=6)
    pre = pp.preprocess_micrograph(jnp.asarray(img.astype(np.float32)))
    params = PickerCNN().init(
        jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 1))
    )["params"]
    patch = int(PARTICLE / pp.BIN_SIZE)
    a = np.asarray(score_micrograph_patches(
        params, pre, patch_size=patch, dtype="float32"
    ))
    b = np.asarray(score_micrograph_patches(
        params, pre, patch_size=patch, dtype="bfloat16"
    ))
    assert np.max(np.abs(a - b)) < 3e-2


def test_fit_cli(synthetic_dataset, tmp_path):
    from repic_tpu.main import main as cli_main

    model_path = str(tmp_path / "m.rptpu")
    cli_main(
        [
            "fit",
            synthetic_dataset["train"][0],
            synthetic_dataset["train"][1],
            model_path,
            "--val_label_dir",
            synthetic_dataset["val"][1],
            "--val_mrc_dir",
            synthetic_dataset["val"][0],
            "--particle_size",
            str(PARTICLE),
            "--batch_size",
            "16",
            "--max_epochs",
            "3",
        ]
    )
    from repic_tpu.models.checkpoint import load_checkpoint

    params, meta = load_checkpoint(model_path)
    assert meta["particle_size"] == PARTICLE
    assert "best_val_error" in meta


def test_star_labels_equal_box_labels(tmp_path):
    """STAR coordinate labels (reference dataLoader.py:340-470 source)
    produce the identical dataset as the equivalent BOX labels."""
    rng = np.random.default_rng(11)
    img, centers = make_micrograph(rng)
    # BOX stores integer corners; use integer centers so both formats
    # encode the identical coordinates
    centers = np.round(centers)
    for kind in ("box", "star"):
        (tmp_path / f"{kind}_mrc").mkdir()
        (tmp_path / f"{kind}_lbl").mkdir()
        mrc.write_mrc(str(tmp_path / f"{kind}_mrc" / "m0.mrc"), img)
    write_box(
        str(tmp_path / "box_lbl" / "m0.box"),
        centers - PARTICLE / 2,
        np.ones(len(centers)),
        PARTICLE,
    )
    # STAR stores centers directly (no corner shift)
    with open(tmp_path / "star_lbl" / "m0.star", "wt") as f:
        f.write("\ndata_\n\nloop_\n")
        f.write("_rlnCoordinateX #1\n_rlnCoordinateY #2\n")
        for cx, cy in centers:
            f.write(f"{cx:.6f}\t{cy:.6f}\n")

    d_box, l_box = data_mod.load_dataset(
        str(tmp_path / "box_mrc"), str(tmp_path / "box_lbl"), PARTICLE
    )
    d_star, l_star = data_mod.load_dataset(
        str(tmp_path / "star_mrc"), str(tmp_path / "star_lbl"), PARTICLE
    )
    np.testing.assert_array_equal(l_box, l_star)
    np.testing.assert_allclose(d_box, d_star, atol=1e-6)


def test_star_labels_deeppicker_suffix(tmp_path):
    """`<stem>_deeppicker.star` files match micrograph `<stem>.mrc`
    (run_deep.sh:27 --coordinate_symbol _deeppicker)."""
    rng = np.random.default_rng(12)
    img, centers = make_micrograph(rng)
    (tmp_path / "mrc").mkdir()
    (tmp_path / "lbl").mkdir()
    mrc.write_mrc(str(tmp_path / "mrc" / "m0.mrc"), img)
    with open(tmp_path / "lbl" / "m0_deeppicker.star", "wt") as f:
        f.write("data_\n\nloop_\n")
        f.write("_rlnCoordinateX #1\n_rlnCoordinateY #2\n")
        for cx, cy in centers:
            f.write(f"{cx:.2f}\t{cy:.2f}\n")
    data, labels = data_mod.load_dataset(
        str(tmp_path / "mrc"), str(tmp_path / "lbl"), PARTICLE
    )
    assert labels.sum() == len(centers)


def test_negative_shortfall_warned(caplog):
    """A micrograph too dense for background sampling must log the
    dropped-negative count, not silently skew the class balance
    (VERDICT r1 weak 7)."""
    import logging

    rng = np.random.default_rng(13)
    size = 200
    img = rng.normal(0, 1, size=(size, size)).astype(np.float32)
    # positives everywhere: no candidate can be 0.5*psize away
    step = 12
    g = np.arange(40, size * 3 - 40, step)
    centers = np.array(
        [(x, y) for x in g for y in g], np.float64
    )
    with caplog.at_level(
        logging.WARNING, logger="repic_tpu.models.data"
    ):
        pos, neg = data_mod.extract_micrograph_patches(
            img, centers, PARTICLE, rng, max_tries=5
        )
    assert len(neg) < len(pos)
    assert any("negative sampling" in r.message for r in caplog.records)


def test_label_discovery_deterministic_collision(tmp_path):
    """mic1.box (curated) must beat mic1_deeppicker.box regardless of
    filesystem enumeration order, and BOX must beat STAR."""
    for name in (
        "mic1.box", "mic1_deeppicker.box", "mic1.star",
        "mic2_deeppicker.star", "mic2.star",
    ):
        (tmp_path / name).write_text("")
    labels = data_mod._discover_labels(str(tmp_path))
    assert labels["mic1"].endswith("mic1.box")
    assert labels["mic2"].endswith("mic2.star")


def _one_micrograph_pair(tmp_path, seed=21):
    rng = np.random.default_rng(seed)
    img, centers = make_micrograph(rng)
    centers = np.round(centers)
    (tmp_path / "mrc").mkdir(exist_ok=True)
    (tmp_path / "lbl").mkdir(exist_ok=True)
    mrc.write_mrc(str(tmp_path / "mrc" / "m0.mrc"), img)
    write_box(
        str(tmp_path / "lbl" / "m0.box"),
        centers - PARTICLE / 2,
        np.ones(len(centers)),
        PARTICLE,
    )
    return img, centers


def test_relion_star_source_matches_box(tmp_path):
    """Particle-STAR source (reference train_type 2): same dataset as
    the per-micrograph BOX source for identical coordinates."""
    _, centers = _one_micrograph_pair(tmp_path)
    star = tmp_path / "particles.star"
    with open(star, "wt") as f:
        f.write("data_\n\nloop_\n")
        f.write(
            "_rlnMicrographName #1\n"
            "_rlnCoordinateX #2\n_rlnCoordinateY #3\n"
        )
        for cx, cy in centers:
            f.write(f"path/to/m0.mrc\t{cx:.1f}\t{cy:.1f}\n")
    d_star, l_star = data_mod.load_dataset_relion_star(
        str(star), str(tmp_path / "mrc"), PARTICLE
    )
    d_box, l_box = data_mod.load_dataset(
        str(tmp_path / "mrc"), str(tmp_path / "lbl"), PARTICLE
    )
    np.testing.assert_array_equal(l_star, l_box)
    np.testing.assert_allclose(d_star, d_box, atol=1e-6)


def test_extracted_source_roundtrip(tmp_path):
    """extract_dataset -> load_dataset_extracted (reference train_type
    3 cross-molecule format), incl. multi-file and per-molecule cap."""
    _one_micrograph_pair(tmp_path)
    n_pos, n_neg = data_mod.extract_dataset(
        str(tmp_path / "mrc"), str(tmp_path / "lbl"), PARTICLE,
        str(tmp_path / "molA.pickle"),
    )
    assert n_pos > 0 and n_neg == n_pos
    import shutil

    shutil.copy(tmp_path / "molA.pickle", tmp_path / "molB.pickle")
    data, labels = data_mod.load_dataset_extracted(
        str(tmp_path), "molA.pickle;molB.pickle"
    )
    assert len(data) == 2 * (n_pos + n_neg)
    assert labels.sum() * 2 == len(labels)
    capped, cl = data_mod.load_dataset_extracted(
        str(tmp_path), "molA.pickle;molB.pickle", per_molecule_cap=3
    )
    assert len(capped) == 2 * 6

    d1, l1 = data_mod.load_dataset_extracted(
        str(tmp_path), "molA.pickle"
    )
    ref, _ = data_mod.load_dataset(
        str(tmp_path / "mrc"), str(tmp_path / "lbl"), PARTICLE
    )
    np.testing.assert_allclose(d1, ref, atol=1e-6)


def test_prepicked_source_selection_modes(tmp_path):
    """Pre-picked results source (reference train_type 4): threshold,
    top-percent, and top-count selection semantics."""
    import pickle

    _, centers = _one_micrograph_pair(tmp_path)
    scores = np.linspace(0.1, 0.9, len(centers))
    rows = [
        [float(x), float(y), float(s), "m0.mrc"]
        for (x, y), s in zip(centers, scores)
    ]
    results = tmp_path / "autopick_results.pickle"
    with open(results, "wb") as f:
        pickle.dump([rows], f)

    # threshold mode: keep scores >= 0.5
    d, l = data_mod.load_dataset_prepicked(
        str(tmp_path / "mrc"), str(results), PARTICLE, select=0.5
    )
    want = int((scores >= 0.5).sum())
    assert l.sum() == want

    # top-percent mode
    d, l = data_mod.load_dataset_prepicked(
        str(tmp_path / "mrc"), str(results), PARTICLE, select=50.0
    )
    assert l.sum() == len(centers) // 2

    # top-count mode
    d, l = data_mod.load_dataset_prepicked(
        str(tmp_path / "mrc"), str(results), PARTICLE, select=101.0
    )
    assert l.sum() == min(101, len(centers))


def test_fit_cli_extracted_source(tmp_path):
    from repic_tpu.main import main as cli_main

    _one_micrograph_pair(tmp_path)
    data_mod.extract_dataset(
        str(tmp_path / "mrc"), str(tmp_path / "lbl"), PARTICLE,
        str(tmp_path / "mol.pickle"),
    )
    model_path = str(tmp_path / "m.rptpu")
    cli_main(
        [
            "fit",
            str(tmp_path),
            "mol.pickle",
            model_path,
            "--source", "extracted",
            "--particle_size", str(PARTICLE),
            "--batch_size", "8",
            "--max_epochs", "2",
            "--val_ratio", "0.25",
        ]
    )
    from repic_tpu.models.checkpoint import load_checkpoint

    params, meta = load_checkpoint(model_path)
    assert meta["particle_size"] == PARTICLE
